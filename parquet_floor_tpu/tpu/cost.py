"""Cost-model routing for ``engine="auto"`` — pick the WINNING engine per
file, not per platform.

The reference exposes one API whose engine is invisible to the caller
(``ParquetReader.java:47-61``); the TPU build's single front door earns
that only if "auto" never routes a file through the losing engine.  Both
engines share the host read+decompress stage, so the differential is:

  host engine:   post-decompress host decode of every chunk
  device engine: ship the arena over the link + fused device decode
                 (+ for the row API: fetch decoded cells back to host)

Those costs are predictable from the footer alone (bytes, codecs,
encodings, optionality) plus a one-time cached link-bandwidth probe:

  * "view"-class chunks (PLAIN, fixed-width, required, flat) host-decode
    at memcpy speed — the device path can only lose the ship time
    (BASELINE.md config #1: 0.73x, the one sub-1x row).
  * "levels"-class chunks (PLAIN fixed-width, optional) pay native level
    decode + scatter on host.
  * "value"-class chunks (dictionary / delta / strings / boolean) pay
    per-value host work — the measured ~0.03-0.05 GB/s that the fused
    device decode beats by 15-50x (BASELINE.md configs #2-5).

Host decode rates are MEASURED per process at first use
(``_probe_host_rates``: ~1 MiB synthetic pages through the real host
page-decode path, cached like the link probes); the module constants
below are the shipped fallback, calibrated from the round-3 stage
tables (docs/DESIGN_DECOMPRESSION.md, BASELINE.md).  Either way the
rates only need to rank the two engines, not predict absolute walls.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..format.parquet_thrift import Encoding, Type
from ..utils import trace

# Differential host post-decompress decode rates, GB/s of page bytes —
# the FALLBACK when the per-process probe cannot run (_probe_host_rates).
HOST_VIEW_GBPS = 4.0     # PLAIN fixed-width required: frombuffer view/copy
HOST_LEVELS_GBPS = 0.4   # PLAIN fixed-width optional: level decode + scatter
HOST_VALUE_GBPS = 0.05   # dict/delta/strings/bool: per-value host decode

# Device-side differential rates/overheads.
DEV_DECODE_GBPS = 8.0    # fused decode, HBM-bandwidth-class
GROUP_OVERHEAD_S = 8e-4  # plan build + dispatch per row group

# Row-API cell materialization (the host cursor boxes each cell through
# per-cell numpy→Python dispatch; the device path converts vectorized —
# tolist once per column + pool-once-per-distinct for dictionaries).
# Host boxing costs differ sharply by column class: a fixed-width
# numeric .item() is cheap; strings/decimals/dict cells pay conversion.
# Calibrated against BASELINE.md's measured 76k rows/s on 16-column
# lineitem (13.2 s wall - 0.6 s host value decode over 2M view-class +
# 14M value-class cells).  The device side's 187k rows/s wall is
# dominated by the D2H fetch, modeled separately (overlapped with
# DEV_CELL_S conversion by the cursor's one-group prefetch).
HOST_CELL_VIEW_S = 0.25e-6   # fixed-width numeric boxing
HOST_CELL_VALUE_S = 0.86e-6  # string/decimal/dict conversion
DEV_CELL_S = 0.1e-6

_CLASS_GBPS = {
    "view": HOST_VIEW_GBPS,
    "levels": HOST_LEVELS_GBPS,
    "value": HOST_VALUE_GBPS,
}

_LEVEL_ENCODINGS = {Encoding.RLE, Encoding.BIT_PACKED}
_FIXED_TYPES = {
    Type.INT32, Type.INT64, Type.FLOAT, Type.DOUBLE,
    Type.FIXED_LEN_BYTE_ARRAY, Type.INT96,
}
_DICT_ENCODINGS = {Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY}

_lock = threading.Lock()
_h2d_gbps: Optional[float] = None
_d2h_model: Optional[tuple] = None  # (fixed_s, gbps)
_host_rates: Optional[Dict[str, float]] = None


def _probe_host_rates() -> Dict[str, float]:
    """One-time host decode-rate calibration, cached per process like
    the link probes.  Times the REAL host page-decode path
    (``pages.decode_data_page`` + ``dense()``) on ~1 MiB synthetic
    pages, one per cost class, so the ranking stands on this machine's
    measured rates instead of the shipped calibration constants
    (VERDICT r4 #3: on a fast-CPU host with a local link, hardcoded
    rates could silently invert the ranking).  The constants remain the
    fallback if the probe fails; rates are floored/capped to keep a
    pathological measurement from producing a nonsense ranking."""
    global _host_rates
    with _lock:
        if _host_rates is not None:
            return _host_rates
    fallback = dict(_CLASS_GBPS)
    try:
        rates = _measure_host_rates()
    except Exception:
        rates = fallback
    rates = {
        k: min(max(v, 1e-4), 100.0) for k, v in rates.items()
    }
    with _lock:
        _host_rates = rates
        return rates


def _measure_host_rates() -> Dict[str, float]:
    import numpy as np

    from ..format import pages as pg
    from ..format.encodings.dictionary import (
        decode_dictionary_page,
        encode_dict_indices,
        encode_dictionary_page,
    )
    from ..format.encodings.plain import ByteArrayColumn, encode_plain
    from ..format.encodings.rle_hybrid import encode_length_prefixed
    from ..format.parquet_thrift import (
        CompressionCodec,
        DataPageHeader,
        PageHeader,
        PageType,
    )
    from ..format.schema import types as t

    def page_of(payload, n):
        return pg.RawPage(
            header=PageHeader(
                type=PageType.DATA_PAGE,
                uncompressed_page_size=len(payload),
                compressed_page_size=len(payload),
                data_page_header=DataPageHeader(
                    num_values=n,
                    encoding=Encoding.PLAIN,
                    definition_level_encoding=Encoding.RLE,
                    repetition_level_encoding=Encoding.RLE,
                ),
            ),
            payload=payload,
        )

    rng = np.random.default_rng(7)
    jobs = {}
    # view: PLAIN fixed-width required — frombuffer-speed
    n = 1 << 17  # 1 MiB of int64
    vals = rng.integers(-(2**40), 2**40, n).astype(np.int64)
    sch_v = t.message("c", t.required(t.INT64).named("x"))
    jobs["view"] = (page_of(encode_plain(vals, Type.INT64), n),
                    sch_v.columns[0], None)
    # levels: PLAIN fixed-width optional — level decode + scatter
    defs = (rng.random(n) > 0.1).astype(np.uint32)
    present = vals[: int(defs.sum())]
    payload = (encode_length_prefixed(defs, 1)
               + encode_plain(present, Type.INT64))
    sch_l = t.message("c", t.optional(t.INT64).named("x"))
    jobs["levels"] = (page_of(payload, n), sch_l.columns[0], None)
    # value: dictionary strings — per-value host work
    pool_strs = [f"value-{i:04d}" for i in range(64)]
    joined = "".join(pool_strs).encode()
    pool = ByteArrayColumn(
        np.cumsum([0] + [len(s) for s in pool_strs]).astype(np.int64),
        np.frombuffer(joined, np.uint8),
    )
    nv = 1 << 17
    idx = rng.integers(0, 64, nv).astype(np.uint32)
    dict_payload = encode_dictionary_page(pool, Type.BYTE_ARRAY)
    dictionary = decode_dictionary_page(dict_payload, 64, Type.BYTE_ARRAY)
    vp = page_of(encode_dict_indices(idx, 64), nv)
    vp.header.data_page_header.encoding = Encoding.RLE_DICTIONARY
    sch_s = t.message(
        "c", t.required(t.BYTE_ARRAY).as_(t.string()).named("x")
    )
    jobs["value"] = (vp, sch_s.columns[0], dictionary)

    rates = {}
    for cls, (page, desc, dictionary) in jobs.items():
        nbytes = len(page.payload)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = pg.decode_data_page(
                page, desc, CompressionCodec.UNCOMPRESSED, dictionary
            )
            if out.def_levels is not None:
                # the host path's null scatter is part of the class cost
                mask = out.def_levels == desc.max_definition_level
                dense = np.zeros(len(mask), dtype=np.int64)
                dense[mask] = out.values
            best = min(best, time.perf_counter() - t0)
        rates[cls] = nbytes / best / 1e9
    return rates


def arena_cap() -> int:
    """The per-launch arena byte budget (PFTPU_ARENA_CAP, default
    64 MiB, ceilinged below the int32 plan limit).  Single source of
    truth: ``TpuRowGroupReader`` sizes its launches with this, and
    ``estimate`` uses it to predict which fields must row-split — and
    therefore host-fall-back when the file has nothing to split on."""
    import os

    return min(
        int(os.environ.get("PFTPU_ARENA_CAP", str(1 << 26))),
        (1 << 31) - (1 << 24),
    )


def _probe_h2d_gbps() -> float:
    """One-time host→device bandwidth probe (8 MiB device_put, best of
    2 after a warm put), cached for the process.  ~20 ms on the
    tunnelled link; the number any shipped-bytes plan is bounded by."""
    global _h2d_gbps
    with _lock:
        if _h2d_gbps is not None:
            return _h2d_gbps
    import jax
    import numpy as np

    buf = np.zeros(8 << 20, dtype=np.uint8)
    jax.block_until_ready(jax.device_put(buf))  # warm
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(buf))
        best = min(best, time.perf_counter() - t0)
    with _lock:
        _h2d_gbps = max(buf.nbytes / best / 1e9, 1e-3)
        return _h2d_gbps


def _probe_d2h_model() -> tuple:
    """One-time device→host cost model ``(fixed_s, gbps)`` from two
    transfer sizes (64 KiB and 1 MiB).  Tunnelled links have a large
    fixed cost (~35 ms) and a slow return path (~11 MB/s — see
    BASELINE.md link characterization); locally-attached devices are
    symmetric.  Probed lazily: ONLY the rows purpose reaches here, and
    only when the pre-fetch estimate already favors the device.  That
    matters because the first D2H can shift a tunnelled link into its
    degraded mode (BASELINE.md) — acceptable here since the row path
    fetches continuously anyway (that mode IS its steady state), while
    the batch purpose never probes D2H and so never triggers it."""
    global _d2h_model
    with _lock:
        if _d2h_model is not None:
            return _d2h_model
    import jax
    import jax.numpy as jnp
    import numpy as np

    times = []
    sizes = [64 << 10, 1 << 20]
    dev_big = jax.device_put(np.zeros(sizes[-1], dtype=np.uint8))
    jax.block_until_ready(dev_big)
    np.asarray(dev_big[: 1 << 10])  # warm the fetch path
    for s in sizes:
        t0 = time.perf_counter()
        np.asarray(jnp.asarray(dev_big[:s]))
        times.append(time.perf_counter() - t0)
    dt = times[1] - times[0]
    gbps = (sizes[1] - sizes[0]) / max(dt, 1e-9) / 1e9
    fixed = max(times[0] - sizes[0] / (gbps * 1e9), 0.0)
    with _lock:
        _d2h_model = (fixed, max(min(gbps, 1e3), 1e-4))
        return _d2h_model


@dataclass
class EngineChoice:
    """The routing decision plus the estimate that produced it."""

    engine: str
    host_s: float = 0.0
    tpu_s: float = 0.0
    reason: str = ""
    bytes_by_class: Dict[str, int] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "engine": self.engine,
            "est_host_s": round(self.host_s, 6),
            "est_tpu_s": round(self.tpu_s, 6),
            "reason": self.reason,
            **{f"{k}_bytes": v for k, v in self.bytes_by_class.items()},
        }


_FIXED_WIDTHS = {
    Type.INT32: 4, Type.INT64: 8, Type.FLOAT: 4, Type.DOUBLE: 8,
    Type.INT96: 12, Type.BOOLEAN: 1,
}


def _dense_byte_estimate(reader, meta, nbytes: int) -> int:
    """Bytes the host fallback actually SHIPS for one chunk: the
    decoded dense stream, not the encoded pages.  Fixed-width types are
    exact from the footer (num_values x width); PLAIN byte arrays are
    ~their page bytes; dictionary-encoded byte arrays expand from
    index stream + pool to gathered values — mirror the 3x ratio the
    fetch estimate uses in the other direction."""
    desc = reader.schema.column(tuple(meta.path_in_schema))
    pt = desc.physical_type
    width = _FIXED_WIDTHS.get(pt)
    if pt == Type.FIXED_LEN_BYTE_ARRAY and desc.type_length:
        width = int(desc.type_length)
    if width is not None:
        return int(meta.num_values or 0) * width
    if set(meta.encodings or []) & _DICT_ENCODINGS:
        return nbytes * 3
    return nbytes


def _dict_pool_estimate(reader, meta, nbytes: int) -> int:
    """Uncompressed dictionary-pool bytes for one chunk.  The footer
    locates the dict page (dictionary_page_offset); its header carries
    the EXACT uncompressed size, so read those ~30 bytes rather than
    guessing — the chunk-wide compression ratio is dominated by the
    repetitive index stream and badly overestimates the pool of unique
    values.  Falls back to a third of the chunk when anything about the
    shape surprises (auto must never fail for routing reasons)."""
    do = meta.dictionary_page_offset
    dp = meta.data_page_offset
    if do is not None and dp is not None and dp > do:
        try:
            from ..format.parquet_thrift import PageHeader

            raw = reader.source.read_at(int(do), min(int(dp - do), 256))
            ph, _ = PageHeader.from_bytes(raw)
            return int(ph.uncompressed_page_size or 0)
        except Exception:
            pass
    return nbytes // 3


def _field_splittable(reader, rg, chunks) -> bool:
    """Footer-cheap mirror of the engine's row-split precondition
    (``engine._read_field_row_split``): every chunk of the field has an
    OffsetIndex AND the chunks share at least one interior page
    boundary to cut on.  Only consulted for over-cap fields, so the
    (tiny) OffsetIndex reads are rare."""
    n = int(rg.num_rows or 0)
    grid = None
    for chunk in chunks:
        if chunk.offset_index_offset is None:
            return False
        oi = reader.read_offset_index(chunk)
        if oi is None or not oi.page_locations:
            return False
        starts = {int(pl.first_row_index or 0) for pl in oi.page_locations}
        grid = starts if grid is None else (grid & starts)
    return bool(grid) and any(0 < p < n for p in grid)


def classify_chunk(desc, meta) -> str:
    """Map one column chunk to its host-decode cost class from footer
    metadata alone: "view" | "levels" | "value"."""
    value_encs = set(meta.encodings or []) - _LEVEL_ENCODINGS
    pt = desc.physical_type
    if value_encs <= {Encoding.PLAIN} and pt in _FIXED_TYPES:
        if desc.max_repetition_level == 0 and desc.max_definition_level == 0:
            return "view"
        if desc.max_repetition_level == 0:
            return "levels"
    return "value"


def estimate(reader, purpose: str = "rows", columns=None) -> EngineChoice:
    """Estimate host-vs-device wall for every row group of ``reader``
    (a ``ParquetFileReader``) and return the routed choice.

    ``purpose``: "rows" adds the device path's decoded-cell fetch cost
    (device→host), which the host engine never pays; "batch" models
    decode-to-device-arrays only (consumers keep arrays on device).
    ``columns``: optional set of top-level field names — only projected
    chunks cost anything, on either engine.
    """
    by_class: Dict[str, int] = {"view": 0, "levels": 0, "value": 0}
    fetch_bytes = 0
    n_groups = 0
    n_cells = 0
    n_value_cells = 0
    pool_metas: list = []
    cap = arena_cap()
    rates = _probe_host_rates()
    unsplit_host_s = 0.0   # device-path host fallback decode (see below)
    unsplit_bytes = 0
    for rg in reader.row_groups:
        n_groups += 1
        # per-field decompressed totals + splittability: a field whose
        # chunks alone exceed the arena cap must row-split to decode on
        # device, which needs an OffsetIndex with an interior page
        # boundary shared by the field's leaves.  Without one the
        # device engine host-falls-back for that field
        # (engine._read_field_host_fallback) — charge those bytes at
        # HOST decode rates on the device side so "auto" ranks the real
        # work, not the fused decode the device never runs.
        field_bytes: Dict[str, int] = {}
        field_chunks: Dict[str, list] = {}
        chunk_rows = []
        for chunk in rg.columns or []:
            meta = chunk.meta_data
            f = meta.path_in_schema[0]
            if columns is not None and f not in columns:
                continue
            desc = reader.schema.column(tuple(meta.path_in_schema))
            nbytes = int(meta.total_uncompressed_size or 0)
            cls = classify_chunk(desc, meta)
            field_bytes[f] = field_bytes.get(f, 0) + nbytes
            field_chunks.setdefault(f, []).append(chunk)
            chunk_rows.append((meta, f, nbytes, cls))
        unsplit_fields = {
            f for f, fb in field_bytes.items()
            if fb > cap
            and not _field_splittable(reader, rg, field_chunks[f])
        }
        for meta, f, nbytes, cls in chunk_rows:
            n_cells += int(meta.num_values or 0)
            if cls == "value":
                n_value_cells += int(meta.num_values or 0)
            if f in unsplit_fields:
                unsplit_host_s += nbytes / (rates[cls] * 1e9)
                unsplit_bytes += _dense_byte_estimate(
                    reader, meta, nbytes
                )
            else:
                by_class[cls] += nbytes
            if set(meta.encodings or []) & _DICT_ENCODINGS:
                # index-form dictionary columns fetch one int32 index
                # per value plus each GROUP's pool — derived from footer
                # facts (num_values + the dict page's header size)
                # instead of a ratio guess.  The runtime cache is
                # content-keyed (api/reader._dict_form_cells), so
                # repeated pools fetch once — but the footer cannot
                # prove repetition, and a sorted/partitioned column
                # carries a DISTINCT pool per group; charging each group
                # keeps the estimate scaling with the real worst case
                # while the common small-pool case stays dominated by
                # the index term anyway
                fetch_bytes += int(meta.num_values or 0) * 4
                # the pool sizes need a (tiny) header read per chunk —
                # deferred to the rows-purpose branch below, the only
                # consumer of fetch_bytes
                pool_metas.append((meta, nbytes))
            else:
                fetch_bytes += nbytes
    total = sum(by_class.values())
    host_s = (
        sum(by_class[c] / (rates[c] * 1e9) for c in rates)
        + unsplit_host_s
    )
    h2d = _probe_h2d_gbps()
    tpu_s = (
        total / (h2d * 1e9)
        + total / (DEV_DECODE_GBPS * 1e9)
        + n_groups * GROUP_OVERHEAD_S
        # unsplittable fields host-decode inside the device engine and
        # ship the DECODED dense bytes (not the encoded pages) — no
        # fused-decode term for them
        + unsplit_host_s
        + unsplit_bytes / (h2d * 1e9)
    )
    if purpose == "rows":
        # cell materialization differs per engine AND per column class
        # (see the HOST_CELL_* calibration note)
        host_s += (
            (n_cells - n_value_cells) * HOST_CELL_VIEW_S
            + n_value_cells * HOST_CELL_VALUE_S
        )
        tpu_s += n_cells * DEV_CELL_S
    if unsplit_bytes:
        by_class["unsplit"] = unsplit_bytes
    choice = EngineChoice(
        engine="tpu" if tpu_s < host_s else "host",
        host_s=host_s,
        tpu_s=tpu_s,
        bytes_by_class=by_class,
    )
    if purpose == "rows" and choice.engine == "tpu":
        # the fetch term can only make the device path worse, and the
        # D2H probe (and the per-chunk dict-pool header reads) are not
        # free — only pay them when they could flip the decision.  The
        # row cursor prefetches one group ahead (api/reader._conv_fut),
        # so the packed fetch of group i+1 overlaps the cell conversion
        # of group i: charge only the fetch time the conversion cannot
        # hide (this matches BASELINE.md's measured lineitem rows
        # walls; a sum-model would misroute the headline file to host).
        # No overlap exists for the FIRST group — scale the hideable
        # conversion by (n_groups-1)/n_groups, so a one-group file pays
        # the full sum
        for meta, nbytes in pool_metas:
            fetch_bytes += _dict_pool_estimate(reader, meta, nbytes)
        fixed, d2h_gbps = _probe_d2h_model()
        fetch_s = n_groups * fixed + fetch_bytes / (d2h_gbps * 1e9)
        hideable = (
            n_cells * DEV_CELL_S * (n_groups - 1) / max(n_groups, 1)
        )
        choice.tpu_s += max(fetch_s - hideable, 0.0)
        if choice.tpu_s >= host_s:
            choice.engine = "host"
    choice.reason = (
        f"est host {choice.host_s * 1e3:.1f} ms vs device "
        f"{choice.tpu_s * 1e3:.1f} ms over {total + unsplit_bytes} "
        f"decoded bytes"
        + (f" ({unsplit_bytes} via host fallback)" if unsplit_bytes else "")
        + f" (link {h2d:.2f} GB/s)"
    )
    return choice


def choose_engine(reader, purpose: str = "rows", columns=None) -> EngineChoice:
    """Route ``engine="auto"`` for an open ``ParquetFileReader``.

    Platform gate first (a non-TPU default backend always routes host —
    the device engine exists to use the TPU); then the x64 environment
    gate (the device engine requires ``jax_enable_x64``; "auto" must
    degrade to host, never error); then the footer cost model.  The
    decision lands in ``utils.trace`` (``trace.decisions()``) when
    tracing is enabled."""
    from .engine import _platform_is_tpu

    if not _platform_is_tpu():
        choice = EngineChoice(engine="host", reason="default backend is not a TPU")
    else:
        import jax

        if not jax.config.jax_enable_x64:
            choice = EngineChoice(
                engine="host",
                reason="jax_enable_x64 is off (device engine needs 64-bit "
                "types; auto degrades to host rather than erroring)",
            )
        else:
            try:
                choice = estimate(reader, purpose=purpose, columns=columns)
            except Exception as e:
                # auto must never fail for routing reasons (probe or
                # footer-shape surprises): the host engine always works
                choice = EngineChoice(
                    engine="host",
                    reason=f"cost estimate failed ({e!r}); host fallback",
                )
    trace.decision("engine.auto", choice.as_dict())
    return choice
