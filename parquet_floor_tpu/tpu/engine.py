"""Batched TPU row-group decode engine — one fused compiled step per group.

Replaces the reference's per-cell pull loop (``ParquetReader.java:176-212``)
with the SURVEY.md §3.2 boundary made real, designed around the two costs
that dominate a real TPU link: per-array transfer overhead and host copies.

Staging (host) packs an entire row group into exactly three objects:

  * ``arena``  — one uint8 buffer holding every decompressed page stream,
    dictionary pool, and host-decoded fallback column.  Pages decompress
    *directly into* the arena (native ``decompress_into``), so bytes are
    touched once on the host.
  * ``slab``   — one int32 buffer holding every run-table plan (absolute
    byte offsets into the arena), page table, and dynamic scalar.
  * ``program``— a static tuple of per-column specs (shapes, dtypes, slab
    offsets).  It is the jit cache key: row groups with the same shape
    signature share one compiled executable.

One ``jax.device_put`` ships arena+slab; one jitted call decodes every
column of the group on device (RLE/bit-packed expansion with per-run bit
widths, dictionary gather, delta prefix-sum, null scatter).  All shape
buckets grow monotonically (high-water marks) so recompiles converge.

Decode paths on device:
  * RLE_DICTIONARY fixed-width + BYTE_ARRAY (mixed per-page bit widths OK)
  * PLAIN fixed-width (paged gather across non-contiguous page streams)
  * PLAIN BOOLEAN (pages as bit-packed runs)
  * DELTA_BINARY_PACKED (multi-page, optional, full int64 via the wide
    reconstruction when the int32 fast path can't prove exactness)
Anything else decodes on the host NumPy engine and ships dense *inside the
same arena* (no extra transfers).
"""

from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..errors import checked_alloc_size
from ..format import codecs
from ..format.encodings import rle_hybrid as e_rle
from ..format.encodings.plain import ByteArrayColumn, decode_plain
from ..format.file_read import ParquetFileReader
from ..format.parquet_thrift import (
    CompressionCodec,
    Encoding,
    PageType,
    Type,
)
from ..format.schema import ColumnDescriptor
from ..utils import trace
from . import bitops
from .kernels import rle_kernel as plk


def _require_x64() -> None:
    """64-bit decode correctness requires x64 (int64 is exact on TPU via
    emulation; float64 is NOT — see the float64 policy).  Checked at reader
    construction rather than forced at import: flipping global dtype
    semantics as an import side effect would silently change the numerics
    of unrelated user code."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "parquet_floor_tpu's TPU engine needs 64-bit JAX types for "
            "INT64/DOUBLE columns: call "
            'jax.config.update("jax_enable_x64", True) before creating a '
            "TpuRowGroupReader"
        )


_NP_DTYPE = {
    Type.INT32: np.int32,
    Type.INT64: np.int64,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
}
_VDTYPE_NAME = {
    Type.INT32: "int32",
    Type.INT64: "int64",
    Type.FLOAT: "float32",
    Type.DOUBLE: "float64",
}
_JNP_BY_NAME = {
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float32": jnp.float32,
    "float64": jnp.float64,
}
_WIDTH_BY_NAME = {"int32": 4, "int64": 8, "float32": 4, "float64": 8, "bool": 1}


def _platform_is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def f64bits_to_f32(bits: jax.Array) -> jax.Array:
    """Convert IEEE-754 double bit patterns (int64) to float32 on device.

    TPU emulates float64 at ~49-bit precision, so a straight f64 bitcast is
    lossy; instead DOUBLE columns decode bit-exactly to int64 and convert to
    the TPU compute dtype with explicit bit math.  Subnormals flush to zero
    (TPU semantics); infinities and NaN are preserved.
    """
    sign = (bits < 0)
    exp = ((bits >> 52) & 0x7FF).astype(jnp.int32)
    mant = (bits & ((1 << 52) - 1))
    # 1.mant as float32: one correctly-rounded int→float conversion, then
    # exact power-of-two scalings — equivalent to rounding the f64 directly.
    # (jnp.exp2 is an approximation on f32; build 2^e exactly from the
    # exponent field instead.)
    frac = (mant | (1 << 52)).astype(jnp.float32) * jnp.float32(2.0**-52)
    e = exp - 1023
    e_clamped = jnp.clip(e, -126, 127)
    pow2 = jax.lax.bitcast_convert_type(
        ((e_clamped + 127) << 23).astype(jnp.int32), jnp.float32
    )
    magnitude = frac * pow2
    magnitude = jnp.where(e > 127, jnp.float32(jnp.inf), magnitude)
    magnitude = jnp.where(e < -126, jnp.float32(0.0), magnitude)  # flush tiny
    magnitude = jnp.where(exp == 0, jnp.float32(0.0), magnitude)
    is_special = exp == 0x7FF
    special = jnp.where(
        mant == 0, jnp.float32(jnp.inf), jnp.float32(jnp.nan)
    )
    magnitude = jnp.where(is_special, special, magnitude)
    return jnp.where(sign, -magnitude, magnitude)


@dataclass
class DeviceColumn:
    """One decoded column living on device.

    For repeated (nested) leaves, ``values`` is the dense *non-null value
    stream* (padded past the true count) and ``def_levels``/``rep_levels``
    are the device-decoded Dremel level arrays — record assembly happens
    on host via :meth:`assemble` (SURVEY.md §7 hard part 5: decode levels
    on TPU, assemble offsets on host).
    """

    descriptor: ColumnDescriptor
    values: jax.Array               # dense (num_rows, ...) values; nulls filled
    mask: Optional[jax.Array]       # True where null; None if required
    lengths: Optional[jax.Array] = None  # for strings: per-row byte lengths
    def_levels: Optional[jax.Array] = None  # repeated cols: int32[n]
    rep_levels: Optional[jax.Array] = None  # repeated cols: int32[n]
    dict_ref: Optional[tuple] = None
    # ``dict_form="index"`` columns: values is the (narrowest-dtype) index
    # stream and dict_ref carries the dictionary pool — ("dev", rows_dev,
    # lens_dev) for strings (shared device pool, content-cached per file)
    # or ("host", typed_numpy_pool) for numerics.  Consumers fetch n×1..4
    # bytes instead of gathered values/byte matrices

    @property
    def is_strings(self) -> bool:
        return self.lengths is not None

    @property
    def is_repeated(self) -> bool:
        return self.rep_levels is not None

    def to_numpy_dense(self):
        return np.asarray(self.values), (None if self.mask is None else np.asarray(self.mask))

    def assemble(self, schema):
        """Assemble a repeated column into a host ``NestedColumn``."""
        if self.rep_levels is None:
            raise ValueError("assemble() requires a repeated column")
        with trace.span("assemble",
                        attrs={"column": ".".join(self.descriptor.path)}):
            return self._assemble(schema)

    def _assemble(self, schema):
        from ..batch.columns import ColumnBatch
        from ..batch.nested import assemble_nested

        defs = np.asarray(self.def_levels).astype(np.uint32)
        reps = np.asarray(self.rep_levels).astype(np.uint32)
        nn = checked_alloc_size(
            np.count_nonzero(defs == self.descriptor.max_definition_level),
            "dense value count", column=".".join(self.descriptor.path),
        )
        if self.lengths is not None:
            rows = np.asarray(self.values)[:nn]
            lens = np.asarray(self.lengths)[:nn].astype(np.int64)
            offsets = np.zeros(nn + 1, dtype=np.int64)
            np.cumsum(lens, out=offsets[1:])
            if nn:
                width = rows.shape[1]
                col_idx = np.arange(width)[None, :]
                flat = rows[col_idx < lens[:, None]]
            else:
                flat = np.zeros(0, np.uint8)
            vals = ByteArrayColumn(offsets, flat)
        else:
            vals = np.asarray(self.values)[:nn]
        batch = ColumnBatch(self.descriptor, len(defs), vals, defs, reps)
        return assemble_nested(schema, batch)


def _concat_repeated_parts(parts: List["DeviceColumn"]) -> "DeviceColumn":
    """Concatenate row-split segments of one REPEATED leaf on device.

    Levels concatenate directly (page-aligned segments never split a
    record when an OffsetIndex exists — pages start at record
    boundaries).  Value streams are dense non-null runs padded past
    each segment's true count, so they pack by scatter: each segment's
    first ``nn`` values land consecutively (``nn`` stays a traced
    device scalar — no device→host sync), the padding scatters out of
    bounds and drops.  The result keeps the engine's repeated-column
    contract (dense stream padded past the true total count)."""
    first = parts[0]
    md = first.descriptor.max_definition_level
    vals = [p.values for p in parts]
    lens = (
        [p.lengths for p in parts] if first.lengths is not None else None
    )
    if lens is not None:
        ml = max(int(v.shape[1]) for v in vals)
        vals = [
            v if int(v.shape[1]) == ml
            else jnp.pad(v, ((0, 0), (0, ml - int(v.shape[1]))))
            for v in vals
        ]
    out_cap = sum(int(v.shape[0]) for v in vals)
    # ONE combined destination index, then one scatter per array (the
    # output is by definition large here — per-segment scatters would
    # copy it k times)
    dest_parts = []
    start = jnp.zeros((), jnp.int32)
    for i, v in enumerate(vals):
        nn = jnp.count_nonzero(parts[i].def_levels == md).astype(jnp.int32)
        idx = jnp.arange(int(v.shape[0]), dtype=jnp.int32)
        dest_parts.append(jnp.where(idx < nn, start + idx, out_cap))
        start = start + nn
    dest = jnp.concatenate(dest_parts)
    out_vals = jnp.zeros(
        (out_cap,) + tuple(vals[0].shape[1:]), vals[0].dtype
    ).at[dest].set(jnp.concatenate(vals), mode="drop")
    out_lens = (
        jnp.zeros((out_cap,), parts[0].lengths.dtype)
        .at[dest].set(jnp.concatenate(lens), mode="drop")
        if lens is not None
        else None
    )
    return DeviceColumn(
        first.descriptor, out_vals, None, out_lens,
        jnp.concatenate([p.def_levels for p in parts]),
        jnp.concatenate([p.rep_levels for p in parts]),
    )


def _concat_device_columns(parts: List["DeviceColumn"]) -> "DeviceColumn":
    """Concatenate row-split segments of one column on device.

    FLAT segment outputs are exact (num_rows,)-shaped (dense scatter
    trims bucket padding), so concatenation reassembles the group
    losslessly; string byte matrices pad to the widest segment first.
    REPEATED leaves pack via :func:`_concat_repeated_parts`.  The
    dict_ref of the last segment wins (content-keyed pools only grow)."""
    if len(parts) == 1:
        return parts[0]
    first = parts[0]
    if first.rep_levels is not None:
        return _concat_repeated_parts(parts)
    lens = None
    if first.lengths is not None:
        ml = max(int(p.values.shape[1]) for p in parts)
        vals = jnp.concatenate([
            p.values if int(p.values.shape[1]) == ml
            else jnp.pad(p.values, ((0, 0), (0, ml - int(p.values.shape[1]))))
            for p in parts
        ])
        lens = jnp.concatenate([p.lengths for p in parts])
    else:
        dts = {str(p.values.dtype) for p in parts}
        if len(dts) > 1:
            # index-form dictionary streams can widen between segments
            # when the pool bucket crosses a dtype boundary
            dt = np.result_type(*sorted(dts))
            vals = jnp.concatenate([p.values.astype(dt) for p in parts])
        else:
            vals = jnp.concatenate([p.values for p in parts])
    mask = (
        jnp.concatenate([p.mask for p in parts])
        if first.mask is not None
        else None
    )
    out = DeviceColumn(first.descriptor, vals, mask, lens)
    out.dict_ref = parts[-1].dict_ref
    return out


class _Fallback(Exception):
    """Signal at layout time: this chunk takes the host NumPy path."""


class _ForceHost(Exception):
    """Signal after arena fill: restage the group with these columns forced
    onto the host path (rare — e.g. delta streams needing >32-bit math).
    Carries every offending column discovered in the pass, so one restage
    handles them all (chunked staging may already have shipped arena
    chunks — restaging per column would multiply that waste)."""

    def __init__(self, *keys: str):
        super().__init__(", ".join(keys))
        self.keys = keys


# ---------------------------------------------------------------------------
# Builders
# ---------------------------------------------------------------------------

class _ArenaBuilder:
    """Reserve byte regions, then fill them all in one pass (decompressing
    straight into the final buffer).

    ``lead`` bytes of zero slack precede the first region (and the cap
    leaves tail slack) so Pallas DMA windows that start before a packed
    run's base or read past its end stay inside the buffer."""

    def __init__(self, lead: int = 0):
        self.size = lead
        self.jobs: List[tuple] = []  # ("d", codec, payload, off, size) | ("c", data, off, size)
        self.inflate_bytes = 0  # decompressed output bytes ("d" jobs only)

    def reserve(self, size: int) -> int:
        off = self.size
        self.size += int(size)
        return off

    def add_decompress(self, codec: int, payload, size: int) -> int:
        off = self.reserve(size)
        self.jobs.append(("d", codec, payload, off, size))
        self.inflate_bytes += int(size)
        return off

    def add_copy(self, data, size: int) -> int:
        off = self.reserve(size)
        self.jobs.append(("c", data, off, size))
        return off

    @staticmethod
    def _run_job(arena: np.ndarray, job: tuple) -> None:
        if job[0] == "d":
            _, codec, payload, off, size = job
            codecs.decompress_into(codec, payload, arena, off, size)
        else:
            _, data, off, size = job
            if size:
                arena[off : off + size] = np.frombuffer(
                    data, dtype=np.uint8, count=size
                )

    def fill(self, arena: np.ndarray, pool: Optional[ThreadPoolExecutor] = None) -> None:
        if pool is not None and len(self.jobs) > 1:
            # jobs write disjoint arena regions; native codecs release the GIL
            list(pool.map(lambda j: self._run_job(arena, j), self.jobs))
        else:
            for job in self.jobs:
                self._run_job(arena, job)

    def fill_chunks(self, arena: np.ndarray, chunk: int,
                    pool: Optional[ThreadPoolExecutor] = None):
        """Fill like :meth:`fill` but yield ``(start, end)`` byte ranges
        as fixed-size chunks of the arena become final, so the caller can
        overlap the device transfer of chunk c with the fill of c+1.
        Jobs are stored in ascending offset order (``reserve`` is
        monotonic), so chunk ``[k·chunk, (k+1)·chunk)`` is final once
        every job starting before its end has run; each chunk's job batch
        runs through ``pool`` (same parallelism as :meth:`fill`)."""
        cap = len(arena)
        done = 0          # start of the first unshipped chunk
        batch: List[tuple] = []

        def flush():
            if pool is not None and len(batch) > 1:
                list(pool.map(lambda j: self._run_job(arena, j), batch))
            else:
                for j in batch:
                    self._run_job(arena, j)
            batch.clear()

        for job in self.jobs:
            start = job[3] if job[0] == "d" else job[2]
            if start >= done + chunk and done + chunk <= cap:
                flush()
                while start >= done + chunk and done + chunk <= cap:
                    yield done, done + chunk
                    done += chunk
            batch.append(job)
        flush()
        while done < cap:
            end = min(done + chunk, cap)
            yield done, end
            done = end


class _I32Builder:
    """Accumulate int32 vectors into one slab; returns element offsets."""

    def __init__(self):
        self.parts: List[np.ndarray] = []
        self.n = 0

    def add(self, arr) -> int:
        a = np.ascontiguousarray(arr, dtype=np.int32).reshape(-1)
        off = self.n
        self.parts.append(a)
        self.n += a.size
        return off

    def build(self, pad_to: int) -> np.ndarray:
        # slab entries come from parsed page geometry (offsets, counts):
        # the blessed cap keeps a corrupt field from sizing the plan slab
        out = np.zeros(
            checked_alloc_size(max(pad_to, self.n, 1), "int32 plan slab"),
            dtype=np.int32,
        )
        pos = 0
        for p in self.parts:
            out[pos : pos + p.size] = p
            pos += p.size
        return out


def _bucket15(n: int, minimum: int = 16) -> int:
    """Round up to a power of two or 1.5× a power of two (≤ 33% waste, few
    distinct buckets — jit-cache-friendly shapes)."""
    if n <= minimum:
        return minimum
    p = 1 << (max(n - 1, 1)).bit_length()  # next pow2 ≥ n
    if n <= (p // 2) + (p // 4):           # 1.5 × pow2/2 fits
        return (p // 2) + (p // 4)
    return p


# ---------------------------------------------------------------------------
# The static per-column program
# ---------------------------------------------------------------------------

class _ColSpec(NamedTuple):
    name: str
    # dict | dict_str | plain | bool | delta | delta1 | delta1w | deltaw |
    # host | host_rows | host_str | hostr | hostr_str | hostr_rows
    kind: str
    n: int           # rows in the group (level positions for repeated cols)
    nexp: int        # value-stream expansion count (n if required, bucketed nn if optional)
    max_def: int
    def_bw: int
    lvl_off: int = -1
    r_lvl: int = 0
    max_rep: int = 0
    rep_off: int = -1   # repetition-level run-table plan (5 × r_rep)
    r_rep: int = 0
    # Pallas expansion plans: () = jnp path; (bw, span_off, n_tiles,
    # interpret) = uniform-width stream expanded by the Pallas kernel
    pl_lvl: tuple = ()
    pl_rep: tuple = ()
    pl_idx: tuple = ()
    idx_off: int = -1   # dict index plan / bool page plan (5 × r_idx)
    r_idx: int = 0
    sc_off: int = -1    # misc dynamic scalars
    pg_off: int = -1    # plain page tables (2 × p_pad: abs base, nn cumsum)
    p_pad: int = 0
    width: int = 0
    vdtype: str = ""
    f64mode: str = ""   # '', 'f32', 'bits', 'f64'
    dict_cap: int = 0
    max_len: int = 0
    extra_idx: int = -1
    mb_off: int = -1
    m_pad: int = 0
    vpm: int = 0


# Fixed arena-transfer chunk: big enough that per-put overhead is noise,
# small enough that the first DMA starts while most of the fill remains.
_SHIP_CHUNK = 4 << 20


@dataclass
class _StagedGroup:
    """Host-staged row group: ship arena+slab, then run the fused program."""

    program: tuple
    arena: np.ndarray
    slab: np.ndarray
    descs: List[ColumnDescriptor]
    extra_keys: List[tuple]            # cache keys, in extras order
    new_extras: List[tuple]            # (key, rows_host, lens_host) to ship
    num_rows: int
    parts: Optional[tuple] = None      # arena chunks already on device
    host_pools: Optional[dict] = None  # spec name → typed numpy pool
    #                                    (index-form numeric dictionaries)
    source: Optional[str] = None       # trace attribution: file path …
    group_index: int = -1              # … and row-group index
    compute: Optional[object] = None   # compute.BuiltCompute (pushdown)
    device: Optional[object] = None    # mesh placement target (None =
    #                                    the reader's default device)


# ---------------------------------------------------------------------------
# Device-side fused decode (traced once per program)
# ---------------------------------------------------------------------------

def _plan5(slab, off: int, r: int):
    p = lax.slice(slab, (off,), (off + 5 * r,)).reshape(5, r)
    return p[0], p[1], p[2], p[3], p[4]


def _expand(arena, slab, off: int, r: int, count: int, pl: tuple = ()):
    if pl:
        # uniform-width stream: Pallas kernel (run-local DMA + bit-matrix
        # contraction) instead of the per-element gather formulation
        pbw, span_off, nt, interp, hbm_plan = pl
        tl = lax.slice(slab, (span_off,), (span_off + nt,))
        th = lax.slice(slab, (span_off + nt,), (span_off + 2 * nt,))
        if hbm_plan:
            # run-heavy stream: plan rides HBM, tiles DMA their window
            plan_flat = lax.slice(slab, (off,), (off + 5 * r,))
            return plk.rle_expand_pallas_inline_hbm(
                arena, plan_flat, r, tl, th, count, pbw, interpret=interp
            )
        oe, k, v, bb, _bw = _plan5(slab, off, r)
        return plk.rle_expand_pallas_inline(
            arena, oe, k, v, bb, tl, th, count, pbw, interpret=interp
        )
    oe, k, v, bb, bw = _plan5(slab, off, r)
    return bitops.rle_expand_bw(arena, oe, k, v, bb, bw, count)


def _typed(u8, count: int, width: int, vdtype: str, f64mode: str):
    rows = u8.reshape(count, width)
    if vdtype == "u8rows":
        return rows
    if vdtype == "bool":
        return rows.reshape(count) != 0
    if vdtype == "float64":
        if f64mode == "f32":
            bits = lax.bitcast_convert_type(rows, jnp.int64).reshape(count)
            return f64bits_to_f32(bits)
        if f64mode == "bits":
            return lax.bitcast_convert_type(rows, jnp.int64).reshape(count)
    return lax.bitcast_convert_type(rows, _JNP_BY_NAME[vdtype]).reshape(count)


def _page_lookup(slab, pg_off: int, p_pad: int, nexp: int):
    """Map each value id to its owning page via the staged 2-row page
    table: returns (page base offsets, page index, within-page index,
    page value count)."""
    base = lax.slice(slab, (pg_off,), (pg_off + p_pad,))
    cum = lax.slice(slab, (pg_off + p_pad,), (pg_off + 2 * p_pad,))
    vid = jnp.arange(nexp, dtype=jnp.int32)
    pgi = jnp.searchsorted(cum, vid, side="right").astype(jnp.int32)
    pgi = jnp.minimum(pgi, p_pad - 1)
    start = jnp.where(pgi == 0, 0, cum[jnp.maximum(pgi - 1, 0)])
    cnt = jnp.maximum(cum[pgi] - start, 1)
    return base, pgi, vid - start, cnt


def _paged_gather(arena, slab, spec: _ColSpec):
    """Gather value bytes across non-contiguous page streams: value id →
    owning page → absolute byte position → width-byte gather."""
    base, pgi, within, _ = _page_lookup(slab, spec.pg_off, spec.p_pad, spec.nexp)
    bytepos = base[pgi] + within * spec.width
    idx = bytepos[:, None] + jnp.arange(spec.width, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, arena.shape[0] - 1)
    return jnp.take(arena, idx.reshape(-1)).reshape(spec.nexp * spec.width)


def _levels_present(arena, slab, spec: _ColSpec):
    levels = _expand(arena, slab, spec.lvl_off, spec.r_lvl, spec.n, spec.pl_lvl)
    return levels == spec.max_def


def _finish_optional(vals, present, lens=None):
    dense = bitops.dense_scatter(vals, present)
    mask = ~present
    dlens = bitops.dense_scatter(lens, present) if lens is not None else None
    return dense, mask, dlens


def _levels_i32(arena, slab, off_slot: int, count: int):
    """Read a host-staged int32 level array out of the arena."""
    l8 = lax.dynamic_slice(arena, (slab[off_slot],), (count * 4,))
    return lax.bitcast_convert_type(l8.reshape(count, 4), jnp.int32).reshape(count)


def _take_opt(a, perm):
    return None if a is None else jnp.take(a, perm, axis=0)


def _decode_col(spec: _ColSpec, arena, slab, extras, perm=None):
    """``perm`` fuses an output row permutation into THIS column's
    program.  It pushes down to the cheapest row-aligned point per kind:
    dictionary kinds permute the (narrow) index stream before the value
    gather, string kinds permute starts/lengths before the byte gather,
    byte-stream-split permutes its page coordinates — for all of those
    the permutation rides index arithmetic the decode already pays for.
    Kinds with no row-aligned intermediate (plain, bool, delta, host
    fallbacks, optional columns after dense scatter) gather their
    outputs instead.  Repeated leaves are not row-aligned at all — the
    caller rejects them before tracing.

    Returns ``(vals, mask, lens, defs, reps, idx)`` — ``idx`` is the
    ROW-ALIGNED dictionary index stream of dictionary kinds (None
    elsewhere), which the pushdown compute tail evaluates against a
    host-precomputed dictionary-match mask.  Programs without a compute
    tail never emit it, so XLA dead-code-eliminates it for free."""
    # in-branch pushdown is only valid while the expansion streams are
    # row-aligned, i.e. for required columns; optional columns permute
    # after _finish_optional densifies them
    rp = perm if spec.max_def == 0 and spec.max_rep == 0 else None
    applied = False
    idx_out = None
    if spec.kind == "host":
        u8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.n * spec.width,))
        vals = _typed(u8, spec.n, spec.width, spec.vdtype, spec.f64mode)
        mask = None
        if spec.max_def > 0:
            m = lax.dynamic_slice(arena, (slab[spec.sc_off + 1],), (spec.n,))
            mask = m != 0
        if perm is not None:
            vals, mask = _take_opt(vals, perm), _take_opt(mask, perm)
        return vals, mask, None, None, None, None
    if spec.kind == "host_rows":
        u8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.n * spec.width,))
        vals = u8.reshape(spec.n, spec.width)
        mask = None
        if spec.max_def > 0:
            m = lax.dynamic_slice(arena, (slab[spec.sc_off + 1],), (spec.n,))
            mask = m != 0
        if perm is not None:
            vals, mask = _take_opt(vals, perm), _take_opt(mask, perm)
        return vals, mask, None, None, None, None
    if spec.kind == "host_str":
        r8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.n * spec.max_len,))
        rows = r8.reshape(spec.n, spec.max_len)
        l8 = lax.dynamic_slice(arena, (slab[spec.sc_off + 1],), (spec.n * 4,))
        lens = lax.bitcast_convert_type(l8.reshape(spec.n, 4), jnp.int32).reshape(spec.n)
        mask = None
        if spec.max_def > 0:
            m = lax.dynamic_slice(arena, (slab[spec.sc_off + 2],), (spec.n,))
            mask = m != 0
        if perm is not None:
            rows, mask, lens = (
                _take_opt(rows, perm), _take_opt(mask, perm),
                _take_opt(lens, perm),
            )
        return rows, mask, lens, None, None, None
    if spec.kind == "hostr":
        # host-decoded repeated column: dense value stream + level arrays
        u8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.nexp * spec.width,))
        vals = _typed(u8, spec.nexp, spec.width, spec.vdtype, spec.f64mode)
        defs = _levels_i32(arena, slab, spec.sc_off + 1, spec.n)
        reps = _levels_i32(arena, slab, spec.sc_off + 2, spec.n)
        return vals, None, None, defs, reps, None
    if spec.kind == "hostr_str":
        r8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.nexp * spec.max_len,))
        rows = r8.reshape(spec.nexp, spec.max_len)
        lens = _levels_i32(arena, slab, spec.sc_off + 1, spec.nexp)
        defs = _levels_i32(arena, slab, spec.sc_off + 2, spec.n)
        reps = _levels_i32(arena, slab, spec.sc_off + 3, spec.n)
        return rows, None, lens, defs, reps, None
    if spec.kind == "hostr_rows":
        # host-decoded repeated FLBA/INT96: dense 2-D byte rows + levels
        u8 = lax.dynamic_slice(arena, (slab[spec.sc_off],), (spec.nexp * spec.width,))
        rows = u8.reshape(spec.nexp, spec.width)
        defs = _levels_i32(arena, slab, spec.sc_off + 1, spec.n)
        reps = _levels_i32(arena, slab, spec.sc_off + 2, spec.n)
        return rows, None, None, defs, reps, None
    # --- expansion-based kinds: dict / dict_str / plain / bool / delta ----
    if spec.kind == "dict":
        idx = _expand(arena, slab, spec.idx_off, spec.r_idx, spec.nexp, spec.pl_idx)
        if rp is not None:
            idx = jnp.take(idx, rp)  # narrow-stream pushdown: ~free
            applied = True
        # clamped gather, not dynamic_slice: the bucketed capacity may
        # overrun the arena tail (padding rows are garbage, never indexed)
        dpos = slab[spec.sc_off] + jnp.arange(
            spec.dict_cap * spec.width, dtype=jnp.int32
        )
        du8 = jnp.take(arena, jnp.clip(dpos, 0, arena.shape[0] - 1))
        dvals = _typed(du8, spec.dict_cap, spec.width, spec.vdtype, spec.f64mode)
        vals = jnp.take(dvals, idx, axis=0)
        lens = None
        idx_out = idx
    elif spec.kind == "dict_str":
        rows_d = extras[2 * spec.extra_idx]
        lens_d = extras[2 * spec.extra_idx + 1]
        idx = _expand(arena, slab, spec.idx_off, spec.r_idx, spec.nexp, spec.pl_idx)
        if rp is not None:
            idx = jnp.take(idx, rp)  # narrow-stream pushdown: ~free
            applied = True
        vals = jnp.take(rows_d, idx, axis=0)
        lens = jnp.take(lens_d, idx)
        idx_out = idx
    elif spec.kind in ("dict_idx", "dict_idx_num"):
        # index-form dictionary column: the index stream IS the output,
        # packed to the narrowest dtype the pool size allows (consumers
        # fetch n×1..4 bytes instead of gathered values; the pool rides
        # extras (strings) or host memory (numerics) untouched)
        idx = _expand(arena, slab, spec.idx_off, spec.r_idx, spec.nexp, spec.pl_idx)
        if rp is not None:
            idx = jnp.take(idx, rp)  # narrow-stream pushdown: ~free
            applied = True
        if spec.dict_cap <= (1 << 8):
            vals = idx.astype(jnp.uint8)
        elif spec.dict_cap <= (1 << 16):
            vals = idx.astype(jnp.uint16)
        else:
            vals = idx
        lens = None
        idx_out = idx
    elif spec.kind == "plain":
        if spec.p_pad == 1:
            u8 = lax.dynamic_slice(
                arena, (slab[spec.pg_off],), (spec.nexp * spec.width,)
            )
        else:
            u8 = _paged_gather(arena, slab, spec)
        vals = _typed(u8, spec.nexp, spec.width, spec.vdtype, spec.f64mode)
        lens = None
    elif spec.kind == "plain_str":
        # variable-length strings: host walked the length chains (native);
        # the device gathers each value's bytes into padded rows
        starts = lax.slice(slab, (spec.pg_off,), (spec.pg_off + spec.nexp,))
        lens = lax.slice(slab, (spec.sc_off,), (spec.sc_off + spec.nexp,))
        if rp is not None:
            # permute the per-row byte coordinates; the (already
            # random-access) byte gather then lands rows pre-shuffled
            starts, lens = jnp.take(starts, rp), jnp.take(lens, rp)
            applied = True
        lane = jnp.arange(spec.max_len, dtype=jnp.int32)[None, :]
        pos = starts[:, None] + lane
        rows = jnp.take(
            arena, jnp.clip(pos, 0, arena.shape[0] - 1).reshape(-1)
        ).reshape(spec.nexp, spec.max_len)
        vals = jnp.where(lane < lens[:, None], rows, jnp.uint8(0))
    elif spec.kind == "bool":
        bits = _expand(arena, slab, spec.idx_off, spec.r_idx, spec.nexp)
        vals = bits.astype(jnp.bool_)
        lens = None
    elif spec.kind == "bss":
        # byte-stream-split: page holds all byte-0s, then byte-1s, …;
        # regather per element — a strided transpose expressed as a gather
        base, pgi, within, cnt = _page_lookup(
            slab, spec.pg_off, spec.p_pad, spec.nexp
        )
        if rp is not None:
            # permute the page coordinates (cnt is row-aligned too); the
            # strided byte gather (already random-access) lands rows
            # pre-shuffled
            pgi = jnp.take(pgi, rp)
            within = jnp.take(within, rp)
            cnt = jnp.take(cnt, rp)
            applied = True
        k = jnp.arange(spec.width, dtype=jnp.int32)[None, :]
        bytepos = base[pgi][:, None] + k * cnt[:, None] + within[:, None]
        u8 = jnp.take(
            arena, jnp.clip(bytepos, 0, arena.shape[0] - 1).reshape(-1)
        )
        vals = _typed(u8, spec.nexp, spec.width, spec.vdtype, spec.f64mode)
        lens = None
    elif spec.kind == "delta1":
        mb = lax.slice(
            slab, (spec.mb_off,), (spec.mb_off + 3 * spec.m_pad,)
        ).reshape(3, spec.m_pad)
        first = slab[spec.sc_off]
        vals = bitops.delta_expand(
            arena, mb[0], mb[1], mb[2], first, spec.nexp, spec.vpm,
            out_dtype=_JNP_BY_NAME[spec.vdtype],
        )
        lens = None
    elif spec.kind == "delta1w":
        mb = lax.slice(
            slab, (spec.mb_off,), (spec.mb_off + 4 * spec.m_pad,)
        ).reshape(4, spec.m_pad)
        vals = bitops.delta_expand_wide(
            arena, mb[0], mb[1], mb[2], mb[3],
            slab[spec.sc_off], slab[spec.sc_off + 1],
            spec.nexp, spec.vpm,
        ).astype(_JNP_BY_NAME[spec.vdtype])
        lens = None
    elif spec.kind == "delta":
        mb = lax.slice(
            slab, (spec.mb_off,), (spec.mb_off + 4 * spec.m_pad,)
        ).reshape(4, spec.m_pad)
        pgt = lax.slice(
            slab, (spec.pg_off,), (spec.pg_off + 3 * spec.p_pad,)
        ).reshape(3, spec.p_pad)
        v32 = bitops.delta_expand_paged(
            arena, mb[0], mb[1], mb[2], mb[3], pgt[0], pgt[1], pgt[2],
            spec.nexp,
        )
        vals = v32.astype(_JNP_BY_NAME[spec.vdtype])
        lens = None
    elif spec.kind == "deltaw":
        mb = lax.slice(
            slab, (spec.mb_off,), (spec.mb_off + 5 * spec.m_pad,)
        ).reshape(5, spec.m_pad)
        pgt = lax.slice(
            slab, (spec.pg_off,), (spec.pg_off + 4 * spec.p_pad,)
        ).reshape(4, spec.p_pad)
        vals = bitops.delta_expand_paged_wide(
            arena, mb[0], mb[1], mb[2], mb[3], mb[4],
            pgt[0], pgt[1], pgt[2], pgt[3], spec.nexp,
        ).astype(_JNP_BY_NAME[spec.vdtype])
        lens = None
    else:  # pragma: no cover - program construction guards this
        raise ValueError(f"unknown column kind {spec.kind!r}")

    if spec.max_rep > 0:
        # repeated leaf: levels decode on device; assembly happens on host
        # (DeviceColumn.assemble) — return the dense value stream + levels
        defs = _expand(arena, slab, spec.lvl_off, spec.r_lvl, spec.n, spec.pl_lvl)
        reps = _expand(arena, slab, spec.rep_off, spec.r_rep, spec.n, spec.pl_rep)
        return vals, None, lens, defs, reps, None
    if spec.max_def > 0:
        present = _levels_present(arena, slab, spec)
        dense, mask, dlens = _finish_optional(vals, present, lens)
        if idx_out is not None:
            # row-aligned index stream for the compute tail (null rows
            # scatter 0; selection leaves AND the presence mask back in)
            idx_out = bitops.dense_scatter(idx_out, present)
        if perm is not None:
            # optional columns are row-aligned only after the dense
            # scatter — permute the densified outputs
            dense = jnp.take(dense, perm, axis=0)
            mask = jnp.take(mask, perm, axis=0)
            dlens = _take_opt(dlens, perm)
            idx_out = _take_opt(idx_out, perm)
        return dense, mask, dlens, None, None, idx_out
    if perm is not None and not applied:
        # kinds with no row-aligned intermediate (plain / bool / delta):
        # gather the finished outputs
        vals = jnp.take(vals, perm, axis=0)
        lens = _take_opt(lens, perm)
    return vals, None, lens, None, None, idx_out


@partial(jax.jit, static_argnums=(0, 1))
def _decode_fused(program: tuple, n_parts: int, *arrays):
    """One compiled decode step for a whole row group.

    ``arrays`` is ``n_parts`` arena chunks (shipped piecewise so the
    transfer overlaps the host fill), then the slab, then the extras;
    the chunks are glued back into one arena on device (a single HBM
    copy — negligible next to the host→device transfer it overlaps)."""
    parts, slab, extras = arrays[:n_parts], arrays[n_parts], arrays[n_parts + 1:]
    arena = parts[0] if n_parts == 1 else jnp.concatenate(parts)
    return tuple(
        _decode_col(spec, arena, slab, extras)[:5] for spec in program
    )


@partial(jax.jit, static_argnums=(0, 1))
def _decode_fused_perm(program: tuple, n_parts: int, *arrays):
    """:func:`_decode_fused` with an output row permutation fused into
    the SAME executable: the trailing array is ``perm`` (int32, one
    entry per row) and every column's row-aligned outputs come back as
    ``x[perm]``.  XLA folds the gather into each column's final output
    write (for gather-formulated kinds it composes with the existing
    index arithmetic), so a loader's window shuffle costs a reordered
    write pattern, not a separate full pass over the decoded bytes.
    Repeated leaves (dense value stream + levels, not row-aligned)
    cannot ride this path — the caller guards."""
    parts, slab = arrays[:n_parts], arrays[n_parts]
    extras, perm = arrays[n_parts + 1:-1], arrays[-1]
    arena = parts[0] if n_parts == 1 else jnp.concatenate(parts)
    return tuple(
        _decode_col(spec, arena, slab, extras, perm)[:5] for spec in program
    )


@partial(jax.jit, static_argnums=(0, 1, 2))
def _decode_fused_compute(program: tuple, n_parts: int, cplan, *arrays):
    """:func:`_decode_fused` with the pushdown COMPUTE TAIL fused into
    the SAME executable (``tpu.compute``, docs/pushdown.md): after the
    per-column decode, the predicate tree evaluates into a selection
    mask and — per ``cplan.mode`` — the launch emits compacted
    surviving rows (``compact``), full columns plus the mask
    (``mask``), or tiny partial-aggregate states (``agg``).  The
    trailing ``cplan.n_masks`` arrays are the host-precomputed
    dictionary-match masks; ``cplan`` itself is static, so every
    distinct predicate/aggregate/capacity is its own executable — and
    its own persistent exec-cache entry."""
    from . import compute as _compute

    parts, slab = arrays[:n_parts], arrays[n_parts]
    rest = arrays[n_parts + 1:]
    nm = cplan.n_masks
    extras = rest[: len(rest) - nm] if nm else rest
    masks = rest[len(rest) - nm:] if nm else ()
    arena = parts[0] if n_parts == 1 else jnp.concatenate(parts)
    full = [_decode_col(spec, arena, slab, extras) for spec in program]
    ctx = {
        spec.name: (f[0], f[1], f[2], f[5])
        for spec, f in zip(program, full)
    }
    sel = _compute.eval_selection(cplan.tree, ctx, masks, cplan.n)
    count = jnp.sum(sel).astype(jnp.int64)
    if cplan.mode == "agg":
        return count, _compute.eval_aggregates(cplan, ctx, sel)
    keep = [
        (spec, f) for spec, f in zip(program, full)
        if spec.name in cplan.ship
    ]
    # projection exprs (docs/query.md) trace into this SAME executable
    # — cplan.exprs is static, so a new expression is a new exec-cache
    # entry exactly like a new predicate
    exprs = getattr(cplan, "exprs", ())
    if cplan.mode == "mask":
        cols = tuple((f[0], f[1], f[2]) for _s, f in keep)
        if not exprs:
            return count, sel, cols
        return count, sel, cols, _compute.eval_exprs(exprs, ctx, cplan.n)
    sel_idx = _compute.compact_indices(sel, cplan.capacity, cplan.n)
    cols = tuple(
        (
            _compute.take_rows(f[0], sel_idx),
            _compute.take_rows(f[1], sel_idx),
            _compute.take_rows(f[2], sel_idx),
        )
        for _s, f in keep
    )
    if not exprs:
        return count, cols
    return count, cols, tuple(
        (
            _compute.take_rows(vals, sel_idx),
            _compute.take_rows(mask, sel_idx),
        )
        for vals, mask in _compute.eval_exprs(exprs, ctx, cplan.n)
    )


@jax.jit
def _take_rows(perm, *arrays):
    return tuple(jnp.take(a, perm, axis=0) for a in arrays)


def _run_fused(program: tuple, n_parts: int, args: list, has_perm: bool,
               device=None, cplan=None):
    """The ONE dispatch of a fused decode launch: every column of the
    row group (levels, index streams, gathers, null scatters, the
    optional fused output permutation, and — with ``cplan`` — the
    pushdown compute tail) executes as a single compiled call —
    ``engine.launches`` counts exactly 1 per in-cap group.  With a
    persistent executable cache active (``PFTPU_EXEC_CACHE``,
    :mod:`.exec_cache`), the compiled executable itself is resolved
    memory → disk → fresh AOT compile, so a repeated shape signature
    skips XLA compilation even across processes.  ``cplan`` is part of
    the static signature, so pushdown programs cache separately per
    predicate/aggregate/capacity."""
    from . import exec_cache

    trace.count("engine.launches")
    if cplan is not None:
        return exec_cache.dispatch(
            _decode_fused_compute, (program, n_parts, cplan), args,
            device=device,
        )
    fn = _decode_fused_perm if has_perm else _decode_fused
    return exec_cache.dispatch(fn, (program, n_parts), args, device=device)


def _permuted_columns(cols: "Dict[str, DeviceColumn]", perm
                      ) -> "Dict[str, DeviceColumn]":
    """Row-permute already-decoded columns in one fused call — the
    fallback for paths where the permutation could not ride the decode
    executable itself (oversized multi-launch groups)."""
    flat, layout = [], []
    for name, dc in cols.items():
        if dc.def_levels is not None or dc.rep_levels is not None:
            from ..errors import UnsupportedFeatureError

            raise UnsupportedFeatureError(
                "out_perm cannot permute repeated columns (the dense "
                "value stream is not row-aligned); project them away"
            )
        arrs = [dc.values, dc.mask, dc.lengths]
        layout.append((name, dc, [a is not None for a in arrs]))
        flat.extend(a for a in arrs if a is not None)
    trace.count("engine.launches")  # the one follow-up gather dispatch
    taken = iter(_take_rows(perm, *flat))
    out: Dict[str, DeviceColumn] = {}
    for name, dc, have in layout:
        vals, mask, lens = (next(taken) if h else None for h in have)
        nd = DeviceColumn(dc.descriptor, vals, mask, lens, None, None)
        nd.dict_ref = dc.dict_ref
        out[name] = nd
    return out


# ---------------------------------------------------------------------------
# Host staging
# ---------------------------------------------------------------------------

@dataclass
class _Pg:
    v: int                      # 1 or 2
    n: int                      # values (levels) in page
    off: int                    # arena offset of the page region (v1) / values (v2)
    size: int                   # region size
    enc: int
    nn: Optional[int] = None    # non-null count (v2 header; v1 computed later)
    lvl_off: int = -1           # v2: arena offset of def-level stream
    lvl_len: int = 0
    rep_off: int = -1           # v2: arena offset of rep-level stream
    rep_len: int = 0


class _DevStage:
    """A chunk headed for the device path.  Raises _Fallback during layout
    when the chunk needs the host engine."""

    def __init__(self, name, chunk, desc: ColumnDescriptor, reader, arena: _ArenaBuilder,
                 raw_pages=None):
        self.name = name
        self.desc = desc
        meta = chunk.meta_data
        pt = desc.physical_type
        codec = meta.codec
        max_def = desc.max_definition_level
        if raw_pages is None:
            raw_pages = reader.read_raw_column_chunk(chunk)
        pages: List[_Pg] = []
        self.dict_off = -1
        self.dict_size = 0
        for page in raw_pages:
            if page.page_type == PageType.DICTIONARY_PAGE:
                dh = page.header.dictionary_page_header
                if dh.encoding not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
                    raise _Fallback("non-PLAIN dictionary page")
                size = page.header.uncompressed_page_size
                self.dict_off = arena.add_decompress(codec, page.payload, size)
                self.dict_size = size
                self.dict_count = int(dh.num_values or 0)
            elif page.page_type == PageType.DATA_PAGE:
                h = page.header.data_page_header
                if max_def > 0 and h.definition_level_encoding not in (
                    Encoding.RLE, None,
                ):
                    raise _Fallback("non-RLE def levels")
                if desc.max_repetition_level > 0 and (
                    h.repetition_level_encoding not in (Encoding.RLE, None)
                ):
                    raise _Fallback("non-RLE rep levels")
                size = page.header.uncompressed_page_size
                off = arena.add_decompress(codec, page.payload, size)
                pages.append(_Pg(1, h.num_values, off, size, h.encoding))
            elif page.page_type == PageType.DATA_PAGE_V2:
                h2 = page.header.data_page_header_v2
                rl = h2.repetition_levels_byte_length or 0
                dl = h2.definition_levels_byte_length or 0
                payload = page.payload
                rep_off = -1
                if rl:
                    rep_off = arena.add_copy(payload[:rl], rl)
                lvl_off = -1
                if dl:
                    lvl_off = arena.add_copy(payload[rl : rl + dl], dl)
                body = payload[rl + dl :]
                vsize = page.header.uncompressed_page_size - rl - dl
                compressed = (
                    h2.is_compressed if h2.is_compressed is not None else True
                )
                if compressed and codec != CompressionCodec.UNCOMPRESSED:
                    val_off = arena.add_decompress(codec, body, vsize)
                else:
                    val_off = arena.add_copy(body, vsize)
                pages.append(
                    _Pg(2, h2.num_values, val_off, vsize, h2.encoding,
                        nn=h2.num_values - (h2.num_nulls or 0),
                        lvl_off=lvl_off, lvl_len=dl,
                        rep_off=rep_off, rep_len=rl)
                )
            elif page.page_type == PageType.INDEX_PAGE:
                continue
            else:
                raise _Fallback(f"page type {page.page_type}")
        if not pages:
            raise _Fallback("empty chunk")
        self.pages = pages
        encs = {p.enc for p in pages}
        if encs <= {Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY}:
            if self.dict_off < 0:
                raise _Fallback("dictionary pages missing")
            if pt in _NP_DTYPE:
                self.kind = "dict"
            elif pt == Type.BYTE_ARRAY:
                self.kind = "dict_str"
            else:
                raise _Fallback(f"dict decode for type {Type.name(pt)}")
        elif encs == {Encoding.PLAIN}:
            if pt == Type.BOOLEAN:
                self.kind = "bool"
            elif pt in _NP_DTYPE:
                self.kind = "plain"
            elif pt == Type.BYTE_ARRAY:
                self.kind = "plain_str"
            elif pt in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
                self.kind = "plain_rows"
            else:
                raise _Fallback(f"PLAIN device decode for {Type.name(pt)}")
        elif (
            pt == Type.BYTE_ARRAY
            and self.dict_off >= 0
            and encs <= {
                Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY,
                Encoding.PLAIN,
            }
        ):
            # dictionary-overflow chunks (pyarrow writes PLAIN fallback
            # pages once the dictionary page limit is hit): host maps every
            # value to (start, len) — via the dict pool for dict pages,
            # via the native chain scan for PLAIN pages — and the device
            # byte gather rides the plain_str path
            self.kind = "mixed_str"
        elif encs == {Encoding.DELTA_BINARY_PACKED} and pt in (
            Type.INT32, Type.INT64,
        ):
            self.kind = "delta"
        elif encs == {Encoding.BYTE_STREAM_SPLIT} and (
            pt in _NP_DTYPE
            or (pt == Type.FIXED_LEN_BYTE_ARRAY and desc.type_length)
        ):
            self.kind = "bss"
        elif encs == {Encoding.DELTA_LENGTH_BYTE_ARRAY} and pt == Type.BYTE_ARRAY:
            # host decodes the (vectorized, tiny) delta length stream; the
            # byte gather then rides the plain_str device machinery
            self.kind = "dlba"
        else:
            raise _Fallback(f"encodings {sorted(encs)}")

    # -- after arena fill ---------------------------------------------------

    def finish(self, arena: np.ndarray, slabb: _I32Builder, eng) -> _ColSpec:
        desc = self.desc
        max_def = desc.max_definition_level
        max_rep = desc.max_repetition_level
        def_bw = e_rle.min_bit_width(max_def)
        rep_bw = e_rle.min_bit_width(max_rep)
        pt = desc.physical_type
        n = sum(p.n for p in self.pages)
        # Two passes: locate every level stream first (prefix reads only),
        # then parse them ALL in one native batch call — the staging loop
        # used to cross the C boundary once per page per category.
        rep_streams: List[tuple] = []
        def_streams: List[tuple] = []
        def_at: List[int] = []     # index into def_streams per page, or -1
        val_offs: List[int] = []
        for p in self.pages:
            if p.v == 1:
                pos = p.off
                if max_rep > 0:
                    ln = int.from_bytes(arena[pos : pos + 4].tobytes(), "little")
                    rep_streams.append((pos + 4, p.n, rep_bw))
                    pos += 4 + ln
                if max_def > 0:
                    ln = int.from_bytes(arena[pos : pos + 4].tobytes(), "little")
                    def_at.append(len(def_streams))
                    def_streams.append((pos + 4, p.n, def_bw))
                    pos += 4 + ln
                else:
                    def_at.append(-1)
                val_offs.append(pos)
            else:
                if max_rep > 0:
                    rep_streams.append((p.rep_off, p.n, rep_bw))
                if max_def > 0:
                    def_at.append(len(def_streams))
                    def_streams.append((p.lvl_off, p.n, def_bw))
                else:
                    def_at.append(-1)
                val_offs.append(p.off)
        nns: List[int] = []
        for p, da in zip(self.pages, def_at):
            if max_def <= 0:
                nn = p.n
            elif p.v == 1:
                # native count_equal scans the stream directly; only the
                # no-native fallback re-parses runs here (v1 pages are
                # the legacy minority — acceptable there)
                pos_s, _, _ = def_streams[da]
                nn = e_rle.count_equal(
                    arena, p.n, def_bw, max_def, pos=pos_s,
                )
            else:
                nn = p.nn
            nns.append(int(nn))
        total_nn = sum(nns)

        spec = dict(
            name=self.name, kind=self.kind, n=n, max_def=max_def, def_bw=def_bw,
            nexp=n, max_rep=max_rep,
        )
        if max_def > 0:
            plan, r_lvl = eng._build_plan5(
                ("r_lvl", self.name), arena, def_streams, n
            )
            spec["lvl_off"] = slabb.add(plan)
            spec["r_lvl"] = r_lvl
            spec["nexp"] = eng._hwm(("nexp", self.name), total_nn)
            spec["pl_lvl"] = eng._pallas_plan(plan, r_lvl, n, def_bw, slabb)
        if max_rep > 0:
            plan, r_rep = eng._build_plan5(
                ("r_rep", self.name), arena, rep_streams, n
            )
            spec["rep_off"] = slabb.add(plan)
            spec["r_rep"] = r_rep
            spec["pl_rep"] = eng._pallas_plan(plan, r_rep, n, rep_bw, slabb)

        if self.kind in ("dict", "dict_str"):
            # collect every page's index stream; the plan builds in one
            # native pass (a bw-0 stream = the all-index-0 page case)
            idx_streams: List[tuple] = []
            idx_bws = set()
            for p, val_off, nn in zip(self.pages, val_offs, nns):
                if nn == 0:
                    # all-null page: no value section — don't even probe
                    # the bit-width byte (it would read the next page)
                    continue
                page_bw = int(arena[val_off])
                if page_bw > 32:
                    raise _ForceHost(self.name)
                idx_streams.append((val_off + 1, nn, page_bw))
                # zero-width pages count as width-1 for the uniformity
                # check (their runs are pure RLE; any kernel width fits)
                idx_bws.add(page_bw or 1)
            plan, r_idx = eng._build_plan5(
                ("r_idx", self.name), arena, idx_streams, total_nn
            )
            spec["idx_off"] = slabb.add(plan)
            spec["r_idx"] = r_idx
            if len(idx_bws) == 1:  # uniform width across the chunk's pages
                spec["pl_idx"] = eng._pallas_plan(
                    plan, r_idx, spec["nexp"], idx_bws.pop(), slabb
                )
            if self.kind == "dict":
                width = np.dtype(_NP_DTYPE[pt]).itemsize
                num_dict = self.dict_size // width
                spec["width"] = width
                spec["vdtype"] = _VDTYPE_NAME[pt]
                spec["f64mode"] = eng._f64mode if pt == Type.DOUBLE else ""
                spec["dict_cap"] = eng._hwm(("dict", self.name), num_dict)
                spec["sc_off"] = slabb.add([self.dict_off])
                if (
                    eng._dict_form == "index"
                    and self.desc.max_repetition_level == 0
                    and not (pt == Type.DOUBLE and eng._f64mode == "f32")
                ):
                    # index-form numerics: decode stops at the (packed)
                    # index stream; the typed pool goes to the consumer
                    # host-side (the arena bytes are transient)
                    spec["kind"] = "dict_idx_num"
                    pool = np.frombuffer(
                        bytes(arena[self.dict_off : self.dict_off + self.dict_size]),
                        dtype=_NP_DTYPE[pt],
                    )
                    if pt == Type.DOUBLE and eng._f64mode == "bits":
                        pool = pool.view(np.int64)
                    spec["_host_pool"] = pool
            else:
                key, cap, max_len = eng._string_dict_key(
                    arena, self.dict_off, self.dict_size, self.name
                )
                spec["dict_cap"] = cap
                spec["max_len"] = max_len
                spec["sc_off"] = slabb.add([self.dict_off])
                spec["extra_idx"] = -2  # patched by the engine (order of use)
                spec["_extra_key"] = key
                if eng._dict_form == "index" and self.desc.max_repetition_level == 0:
                    # dict-form output: decode stops at the index stream;
                    # the pool still ships (extras) for the consumer
                    spec["kind"] = "dict_idx"
        elif self.kind in ("plain_str", "dlba", "mixed_str"):
            from ..format.encodings import delta as e_delta

            dict_starts = dict_lens = None
            if self.kind == "mixed_str":
                region = arena[self.dict_off : self.dict_off + self.dict_size]
                # exact count from the dictionary page header: the Python
                # scan fallback decodes exactly `count` entries (an
                # overestimate would read past the pool and raise)
                dict_starts, dict_lens = _scan_plain_strings(
                    region, self.dict_count
                )
                if len(dict_starts) != self.dict_count:
                    raise _ForceHost(self.name)
                dict_starts = dict_starts + self.dict_off
            starts_all = []
            lens_all = []
            for p, val_off, nn in zip(self.pages, val_offs, nns):
                if not nn:
                    continue
                # nn is the page header's value count — bless it before
                # it sizes any array (loop targets are never FL-ALLOC safe)
                nv = checked_alloc_size(nn, "string page value count")
                if self.kind == "mixed_str" and p.enc in (
                    Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY,
                ):
                    page_bw = int(arena[val_off])
                    if page_bw > 32:
                        raise _ForceHost(self.name)
                    if page_bw == 0:
                        idx = np.zeros(nv, np.int64)
                    else:
                        idx, _ = e_rle.decode_rle_hybrid(
                            arena, nn, page_bw, pos=val_off + 1
                        )
                        idx = idx.astype(np.int64)
                    if idx.size and int(idx.max()) >= len(dict_starts):
                        raise ValueError(
                            f"dictionary index out of range in {self.name}"
                        )
                    starts_all.append(dict_starts[idx])
                    lens_all.append(dict_lens[idx])
                    continue
                if self.kind == "dlba":
                    region_size = p.off + p.size - val_off
                    lengths, data_pos = e_delta.decode_delta_binary_packed(
                        arena[val_off : p.off + p.size].tobytes()
                    )
                    if len(lengths) != nn:
                        raise _ForceHost(self.name)
                    total_bytes = int(lengths.sum())
                    if (
                        (nn and int(lengths.min()) < 0)
                        or data_pos + total_bytes > region_size
                    ):
                        raise ValueError(
                            f"DELTA_LENGTH_BYTE_ARRAY page of {self.name}: "
                            "length stream overruns the page"
                        )
                    starts = np.zeros(nv, np.int64)
                    np.cumsum(lengths[:-1], out=starts[1:])
                    starts += data_pos
                else:
                    region = arena[val_off : p.off + p.size]
                    starts, lengths = _scan_plain_strings(region, nn)
                    if len(starts) != nn:
                        raise ValueError(
                            f"PLAIN BYTE_ARRAY page of {self.name}: found "
                            f"{len(starts)} values, header said {nn}"
                        )
                starts_all.append(starts + val_off)
                lens_all.append(lengths)
            starts = (
                np.concatenate(starts_all) if starts_all else np.zeros(0, np.int64)
            )
            lengths = (
                np.concatenate(lens_all) if lens_all else np.zeros(0, np.int64)
            )
            if starts.size and starts.max() >= 2**31:
                raise _ForceHost(self.name)
            max_len = eng._hwm(
                ("pstr_len", self.name),
                max(int(lengths.max()) if lengths.size else 1, 1),
            )
            nexp = spec["nexp"]
            spec["kind"] = "plain_str"  # dlba shares the device string path
            spec["max_len"] = max_len
            spec["pg_off"] = slabb.add(bitops.pad_to(starts.astype(np.int64), nexp))
            spec["sc_off"] = slabb.add(bitops.pad_to(lengths.astype(np.int64), nexp))
        elif self.kind in ("plain", "plain_rows"):
            if self.kind == "plain_rows":
                width = desc.type_length if pt == Type.FIXED_LEN_BYTE_ARRAY else 12
                if not width:
                    raise _ForceHost(self.name)
                spec["kind"] = "plain"
                spec["vdtype"] = "u8rows"
            else:
                width = np.dtype(_NP_DTYPE[pt]).itemsize
                spec["vdtype"] = _VDTYPE_NAME[pt]
                spec["f64mode"] = eng._f64mode if pt == Type.DOUBLE else ""
            spec["width"] = width
            # collapse contiguous page streams into one (required v1 pages
            # decompress back-to-back in the arena); only required columns
            # may use the dynamic_slice fast path — optional columns pad
            # nexp beyond nn, which must clamp per element (paged gather)
            contiguous = max_def == 0 and all(
                val_offs[i] == val_offs[i - 1] + nns[i - 1] * width
                for i in range(1, len(val_offs))
            )
            if contiguous:
                p_pad = 1
                page_tbl = np.array([val_offs[0], total_nn], dtype=np.int64)
            else:
                page_tbl, p_pad = _page_table(
                    val_offs, nns, total_nn, eng, self.name
                )
            spec["pg_off"] = slabb.add(page_tbl)
            spec["p_pad"] = p_pad
        elif self.kind == "bss":
            if pt in _NP_DTYPE:
                width = np.dtype(_NP_DTYPE[pt]).itemsize
                spec["vdtype"] = _VDTYPE_NAME[pt]
                spec["f64mode"] = eng._f64mode if pt == Type.DOUBLE else ""
            else:
                width = desc.type_length
                spec["vdtype"] = "u8rows"
            spec["width"] = width
            page_tbl, p_pad = _page_table(val_offs, nns, total_nn, eng, self.name)
            spec["pg_off"] = slabb.add(page_tbl)
            spec["p_pad"] = p_pad
        elif self.kind == "bool":
            pg_tables = [
                (np.array([[1, nn, val_off, 0]], dtype=np.int64), 1)
                for val_off, nn in zip(val_offs, nns)
                if nn
            ]
            r_idx = eng._hwm(("pages", self.name), max(len(pg_tables), 1), minimum=4)
            spec["idx_off"] = slabb.add(
                bitops.tables_to_plan5(pg_tables, total_nn, r_idx)
            )
            spec["r_idx"] = r_idx
            spec["vdtype"] = "bool"
        elif self.kind == "delta" and len(self.pages) == 1 and max_def == 0:
            # single required page: the miniblock id is a plain division —
            # cheaper on device than the segmented searchsorted form
            val_off = val_offs[0]
            end = self.pages[0].off + self.pages[0].size
            wide_ok = np.dtype(_NP_DTYPE[pt]).itemsize > 4
            plan = parse_delta_plan(
                arena[val_off:end], _NP_DTYPE[pt], allow_wide=wide_ok
            )
            if plan is None:
                raise _ForceHost(self.name)
            m_pad = checked_alloc_size(
                eng._hwm(("mb", self.name), len(plan["mb_bw"]), minimum=4),
                "delta miniblock pad",
            )
            k = len(plan["mb_bytebase"])
            bytebase = plan["mb_bytebase"] + val_off
            if bytebase.max(initial=0) >= 2**31:
                raise _ForceHost(self.name)
            if plan["wide"]:
                # int64 reconstruction: 64-bit constants ride the int32
                # slab as (low, high) word rows
                spec["kind"] = "delta1w"
                mb = np.zeros((4, m_pad), dtype=np.int64)
                mb[0, :k] = bytebase
                mb[1, :k] = plan["mb_bw"]
                mb[2, :k] = plan["mb_min_delta"] & 0xFFFFFFFF
                mb[3, :k] = plan["mb_min_delta"] >> 32
                first = plan["first_value"]
                # int64 array first: numpy wraps array casts to int32 but
                # range-checks bare python ints
                spec["sc_off"] = slabb.add(
                    np.array([first & 0xFFFFFFFF, first >> 32], np.int64)
                )
            else:
                spec["kind"] = "delta1"
                mb = np.zeros((3, m_pad), dtype=np.int64)
                mb[0, :k] = bytebase
                mb[1, :k] = plan["mb_bw"]
                mb[2, :k] = plan["mb_min_delta"]
                spec["sc_off"] = slabb.add([plan["first_value"]])
            spec["mb_off"] = slabb.add(mb)
            spec["m_pad"] = m_pad
            spec["vpm"] = plan["values_per_miniblock"]
            spec["vdtype"] = _VDTYPE_NAME[pt]
        elif self.kind == "delta":
            mb_start: List[int] = []
            mb_bytebase: List[int] = []
            mb_bw: List[int] = []
            mb_min: List[int] = []
            pg_first: List[int] = []
            pg_start: List[int] = []
            running = 0
            live_nns: List[int] = []
            wide_ok = np.dtype(_NP_DTYPE[pt]).itemsize > 4
            wide = False
            for p, val_off, nn in zip(self.pages, val_offs, nns):
                if not nn:
                    # all-null page: no value section to parse
                    continue
                end = p.off + p.size
                plan = parse_delta_plan(
                    arena[val_off:end], _NP_DTYPE[pt], allow_wide=wide_ok
                )
                if plan is None or plan["total"] != nn:
                    raise _ForceHost(self.name)
                wide = wide or plan["wide"]
                vpm = plan["values_per_miniblock"]
                pg_first.append(plan["first_value"])
                pg_start.append(running)
                k_mb = len(plan["mb_bw"])
                mb_start.append(
                    running + 1 + np.arange(k_mb, dtype=np.int64) * vpm
                )
                mb_bytebase.append(plan["mb_bytebase"] + val_off)
                mb_bw.append(plan["mb_bw"])
                mb_min.append(plan["mb_min_delta"])
                running += nn
                live_nns.append(nn)
            c_start = np.concatenate(mb_start) if mb_start else np.zeros(0, np.int64)
            c_bytebase = np.concatenate(mb_bytebase) if mb_bytebase else np.zeros(0, np.int64)
            c_bw = np.concatenate(mb_bw) if mb_bw else np.zeros(0, np.int64)
            c_min = np.concatenate(mb_min) if mb_min else np.zeros(0, np.int64)
            m_pad = checked_alloc_size(
                eng._hwm(("mb", self.name), max(len(c_bw), 1), minimum=4),
                "delta miniblock pad",
            )
            rows = 5 if wide else 4
            mb = np.zeros((rows, m_pad), dtype=np.int64)
            mb[0] = 2**31 - 1  # out-start sentinel for pad miniblocks
            k = len(c_bw)
            if k:
                mb[0, :k] = c_start
                mb[1, :k] = c_bytebase
                mb[2, :k] = c_bw
                if wide:
                    mb[3, :k] = c_min & 0xFFFFFFFF
                    mb[4, :k] = c_min >> 32
                else:
                    mb[3, :k] = c_min
            if mb[1].max(initial=0) >= 2**31:
                raise _ForceHost(self.name)
            spec["mb_off"] = slabb.add(mb)
            spec["m_pad"] = m_pad
            # fresh name: `p_pad` is also bound from _page_table unpacks
            # in this scope, which FL-ALLOC001's fixpoint cannot bless
            dp_pad = checked_alloc_size(
                eng._hwm(("pages", self.name), len(self.pages), minimum=4),
                "delta page-table pad",
            )
            firsts = np.asarray(pg_first, np.int64)
            if wide:
                spec["kind"] = "deltaw"
                pgt = np.zeros((4, dp_pad), dtype=np.int64)
                pgt[0, : len(pg_start)] = pg_start
                pgt[1, : len(pg_first)] = firsts & 0xFFFFFFFF
                pgt[2, : len(pg_first)] = firsts >> 32
                pgt[3] = total_nn
                pgt[3, : len(live_nns)] = np.cumsum(live_nns)
            else:
                pgt = np.zeros((3, dp_pad), dtype=np.int64)
                pgt[0, : len(pg_start)] = pg_start
                pgt[1, : len(pg_first)] = firsts
                pgt[2] = total_nn
                pgt[2, : len(live_nns)] = np.cumsum(live_nns)
            spec["pg_off"] = slabb.add(pgt)
            spec["p_pad"] = dp_pad
            spec["vdtype"] = _VDTYPE_NAME[pt]
        return spec


class _HostStage:
    """A chunk decoded by the host engine, packed dense into the arena."""

    def __init__(self, name, chunk, desc, eng, arena: _ArenaBuilder,
                 covered=None, group_rows: int = 0, raw_pages=None):
        self.name = name
        self.desc = desc
        if covered is not None:
            batch = eng.reader._read_chunk_ranges(
                chunk, covered, group_rows, raw_pages=raw_pages
            )
        else:
            batch = eng.reader.read_column_chunk(chunk)
        n = batch.num_values
        self.n = n
        self.max_def = 0
        self.max_rep = desc.max_repetition_level
        self.offs: Dict[str, int] = {}
        if self.max_rep > 0:
            # repeated column: ship the dense non-null value stream plus
            # the int32 level arrays; assembly happens on host after decode
            vals = batch.values
            self.nn = len(vals)
            if isinstance(vals, ByteArrayColumn):
                max_len = eng._hwm(
                    ("hs_len", name),
                    max((int(vals.lengths().max()) if len(vals) else 1), 1),
                )
                rows, lengths, _ = _padded_rows(vals, pad_len=max_len)
                self.kind = "hostr_str"
                self.max_len = max_len
                self.offs["rows"] = arena.add_copy(rows, rows.size)
                self.offs["lens"] = arena.add_copy(
                    lengths.astype(np.int32), self.nn * 4
                )
            elif vals.ndim == 2:
                # repeated FLBA/INT96 byte rows (e.g. dict-encoded
                # fixed-width leaves whose chunk fell back to host
                # decode): ship the dense 2-D u8 stream as-is — the
                # reference's engine decodes any physical type at any
                # repetition level (ParquetReader.java:147-163), so the
                # device engine must never refuse a file shape the host
                # engine handles
                self.kind = "hostr_rows"
                self.width = vals.shape[1]
                d = np.ascontiguousarray(vals, dtype=np.uint8)
                self.offs["vals"] = arena.add_copy(d, d.size)
            else:
                if vals.dtype == np.bool_:
                    vals = vals.astype(np.uint8)
                    self.vdtype = "bool"
                elif vals.dtype == np.float64 and eng._f64mode == "f32":
                    vals = vals.astype(np.float32)
                    self.vdtype = "float32"
                elif vals.dtype == np.float64 and eng._f64mode == "bits":
                    vals = vals.view(np.int64)
                    self.vdtype = "int64"
                else:
                    self.vdtype = vals.dtype.name
                self.kind = "hostr"
                self.width = vals.dtype.itemsize
                d = np.ascontiguousarray(vals)
                self.offs["vals"] = arena.add_copy(d.view(np.uint8), d.nbytes)
            defs = np.ascontiguousarray(batch.def_levels, dtype=np.int32)
            reps = np.ascontiguousarray(batch.rep_levels, dtype=np.int32)
            self.offs["defs"] = arena.add_copy(defs.view(np.uint8), n * 4)
            self.offs["reps"] = arena.add_copy(reps.view(np.uint8), n * 4)
            return
        dense, mask = batch.dense()
        self.max_def = 1 if mask is not None else 0
        if isinstance(dense, ByteArrayColumn):
            max_len = eng._hwm(
                ("hs_len", name), max((int(dense.lengths().max()) if n else 1), 1)
            )
            rows, lengths, _ = _padded_rows(dense, pad_len=max_len)
            self.kind = "host_str"
            self.max_len = max_len
            self.offs["rows"] = arena.add_copy(rows, rows.size)
            self.offs["lens"] = arena.add_copy(
                lengths.astype(np.int32), n * 4
            )
        elif dense.ndim == 2:
            self.kind = "host_rows"
            self.width = dense.shape[1]
            d = np.ascontiguousarray(dense, dtype=np.uint8)
            self.offs["vals"] = arena.add_copy(d, d.size)
        else:
            if dense.dtype == np.float64:
                if eng._f64mode == "f32":
                    dense = dense.astype(np.float32)
                elif eng._f64mode == "bits":
                    dense = dense.view(np.int64)
            self.kind = "host"
            self.vdtype = {
                "int32": "int32", "int64": "int64", "float32": "float32",
                "float64": "float64", "bool": "bool", "uint8": "u8rows",
            }[dense.dtype.name]
            self.width = dense.dtype.itemsize
            d = np.ascontiguousarray(dense)
            self.offs["vals"] = arena.add_copy(d.view(np.uint8), d.nbytes)
        if mask is not None:
            self.offs["mask"] = arena.add_copy(
                mask.astype(np.uint8), n
            )

    def finish(self, arena, slabb: _I32Builder, eng) -> dict:
        spec = dict(
            name=self.name, kind=self.kind, n=self.n, nexp=self.n,
            max_def=self.max_def, def_bw=0,
        )
        if self.kind == "hostr":
            spec["sc_off"] = slabb.add(
                [self.offs["vals"], self.offs["defs"], self.offs["reps"]]
            )
            spec["nexp"] = self.nn
            spec["max_rep"] = self.max_rep
            spec["max_def"] = self.desc.max_definition_level
            spec["width"] = self.width
            spec["vdtype"] = self.vdtype
            spec["f64mode"] = ""
            return spec
        if self.kind == "hostr_str":
            spec["sc_off"] = slabb.add(
                [self.offs["rows"], self.offs["lens"], self.offs["defs"],
                 self.offs["reps"]]
            )
            spec["nexp"] = self.nn
            spec["max_rep"] = self.max_rep
            spec["max_def"] = self.desc.max_definition_level
            spec["max_len"] = self.max_len
            return spec
        if self.kind == "hostr_rows":
            spec["sc_off"] = slabb.add(
                [self.offs["vals"], self.offs["defs"], self.offs["reps"]]
            )
            spec["nexp"] = self.nn
            spec["max_rep"] = self.max_rep
            spec["max_def"] = self.desc.max_definition_level
            spec["width"] = self.width
            spec["vdtype"] = "u8rows"
            return spec
        if self.kind == "host_str":
            sc = [self.offs["rows"], self.offs["lens"]]
            if self.max_def:
                sc.append(self.offs["mask"])
            spec["sc_off"] = slabb.add(sc)
            spec["max_len"] = self.max_len
        else:
            sc = [self.offs["vals"]]
            if self.max_def:
                sc.append(self.offs["mask"])
            spec["sc_off"] = slabb.add(sc)
            spec["width"] = self.width
            spec["vdtype"] = self.vdtype if self.kind == "host" else "u8rows"
        return spec


def _padded_rows(col: ByteArrayColumn, pad_len: Optional[int] = None,
                 pad_rows: Optional[int] = None):
    """Vectorized (n, max_len) uint8 matrix + lengths from a ByteArrayColumn
    (the device-friendly string layout)."""
    lengths = col.lengths().astype(np.int32)
    n = len(col)
    # lengths derive from parsed offsets: a corrupt offset pair must not
    # size a (rows, width) matrix — both dimensions flow through the cap
    max_len = checked_alloc_size(
        max(int(lengths.max()) if n else 1, 1), "padded string width"
    )
    if pad_len is not None:
        if pad_len < max_len:
            raise ValueError("pad_len shorter than longest string")
        max_len = checked_alloc_size(pad_len, "padded string width")
    n_rows = checked_alloc_size(
        n if pad_rows is None else pad_rows, "padded string rows"
    )
    if n_rows < n:
        raise ValueError("pad_rows smaller than row count")
    out_rows = np.zeros((n_rows, max_len), np.uint8)
    out_lens = np.zeros(n_rows, np.int32)
    out_lens[:n] = lengths
    data = col.data
    if n and len(data):
        idx = col.offsets[:-1, None] + np.arange(max_len)[None, :]
        valid = np.arange(max_len)[None, :] < lengths[:, None]
        out_rows[:n] = np.where(
            valid, data[np.minimum(idx, len(data) - 1)], np.uint8(0)
        )
    return out_rows, out_lens, max_len


def _wrap64(v: int) -> int:
    """Clamp a decoded zigzag varint to int64 wraparound semantics."""
    return ((v + (1 << 63)) & ((1 << 64) - 1)) - (1 << 63)


def parse_delta_plan(data_u8: np.ndarray, dtype, allow_wide=False) -> Optional[dict]:
    """Host parse of a DELTA_BINARY_PACKED stream into a device miniblock
    plan.  Returns None (→ host fallback) only for malformed streams.

    The plan's ``"wide"`` flag selects the device arithmetic: False = the
    int32 fast path (always exact for int32 output, where wraparound is
    the spec semantics; for int64 output, proven exact by interval
    arithmetic over every reachable *prefix sum*); True = full int64
    reconstruction (miniblock widths ≤ 64, any first/min_delta).  Without
    ``allow_wide`` the wide cases return None instead."""
    try:
        from ..native import binding as _nb
    except ImportError:  # pragma: no cover - native lib is optional
        _nb = None
    if _nb is not None and _nb.available():
        # native twin of the walk below (the varint/miniblock scan was
        # staging's hottest pure-Python loop on 1000-column tables)
        return _nb.delta_parse_plan(
            data_u8, np.dtype(dtype).itemsize, allow_wide
        )
    data = bytes(data_u8)
    pos = 0
    block_size, pos = e_rle._read_varint(data, pos)
    n_mini, pos = e_rle._read_varint(data, pos)
    total, pos = e_rle._read_varint(data, pos)
    first, pos = _read_zigzag(data, pos)
    first = _wrap64(first)
    if n_mini == 0 or block_size % n_mini:
        return None
    per_mini = block_size // n_mini
    check_range = np.dtype(dtype).itemsize > 4
    i32 = (-(2**31), 2**31 - 1)
    wide = not (-(2**31) <= first < 2**31)
    if wide and not allow_wide:
        return None
    lo = hi = first  # reachable value interval across all prefix sums
    mb_bytebase, mb_bw, mb_min = [], [], []
    got = 0
    n_deltas = total - 1
    while got < n_deltas:
        min_delta, pos = _read_zigzag(data, pos)
        min_delta = _wrap64(min_delta)
        if not (-(2**31) <= min_delta < 2**31):
            if not allow_wide:
                return None
            wide = True
        widths = data[pos : pos + n_mini]
        pos += n_mini
        for m in range(n_mini):
            if got >= n_deltas:
                break
            bwm = widths[m]
            if bwm > 64:
                return None  # malformed: the spec caps deltas at 64 bits
            if bwm > 32:
                if not allow_wide:
                    return None
                wide = True
            count = min(per_mini, n_deltas - got)
            if check_range and not wide:
                # Every delta in this miniblock lies in [d_lo, d_hi]; the
                # lowest reachable prefix adds count*d_lo when d_lo < 0
                # (monotone dip), else never dips below the entry value —
                # symmetrically for the high side.
                d_lo = min_delta
                d_hi = min_delta + ((1 << bwm) - 1)
                lo += count * d_lo if d_lo < 0 else 0
                hi += count * d_hi if d_hi > 0 else 0
                if lo < i32[0] or hi > i32[1]:
                    if not allow_wide:
                        return None
                    wide = True
            mb_bytebase.append(pos)
            mb_bw.append(bwm)
            mb_min.append(min_delta)
            got += count
            pos += per_mini * bwm // 8
    return {
        "mb_bytebase": np.array(mb_bytebase or [0], np.int64),
        "mb_bw": np.array(mb_bw or [0], np.int64),
        "mb_min_delta": np.array(mb_min or [0], np.int64),
        "first_value": int(first),
        "values_per_miniblock": per_mini,
        "total": total,
        "end_pos": pos,
        "wide": wide,
    }


def _read_zigzag(data, pos):
    v, pos = e_rle._read_varint(data, pos)
    return (v >> 1) ^ -(v & 1), pos


def _page_table(val_offs, nns, total_nn: int, eng, name: str):
    """Staged 2-row page table (base offsets; value cumsum) padded to the
    column's page-count bucket — the host half of ``_page_lookup``."""
    p_pad = eng._hwm(("pages", name), len(val_offs), minimum=4)
    base = bitops.pad_to(np.asarray(val_offs, np.int64), p_pad)
    cum = bitops.pad_to(
        np.cumsum(np.asarray(nns, np.int64)), p_pad, fill=total_nn
    )
    return np.concatenate([base, cum]), p_pad


def _scan_plain_strings(region: np.ndarray, count: int):
    """Walk a PLAIN BYTE_ARRAY length chain → (starts, lengths) int64 arrays
    (region-relative).  Native single pass when built; Python fallback.
    Malformed chains raise (never silently mis-decode)."""
    try:
        from ..native import binding as _nb
    except ImportError:
        _nb = None
    if _nb is not None and _nb.available():
        return _nb.plain_ba_scan(region, count)
    b = region.tobytes()
    end = len(b)
    cnt = checked_alloc_size(count, "PLAIN string count")
    starts = np.zeros(cnt, np.int64)
    lengths = np.zeros(cnt, np.int64)
    pos = 0
    for i in range(count):
        if pos + 4 > end:
            raise ValueError("PLAIN BYTE_ARRAY stream truncated")
        ln = int.from_bytes(b[pos : pos + 4], "little")
        if pos + 4 + ln > end:
            raise ValueError("PLAIN BYTE_ARRAY value overruns stream")
        starts[i] = pos + 4
        lengths[i] = ln
        pos += 4 + ln
    return starts, lengths


def _count_plain_strings(data_u8) -> int:
    """Count values in a PLAIN BYTE_ARRAY stream (walk the length chain)."""
    pos = 0
    n = 0
    total = len(data_u8)
    b = data_u8 if isinstance(data_u8, bytes) else data_u8.tobytes()
    while pos < total:
        ln = int.from_bytes(b[pos : pos + 4], "little")
        pos += 4 + ln
        n += 1
    return n


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class TpuRowGroupReader:
    """Decode row groups of a parquet file into device-resident columns.

    The batch-columnar sibling of the row-streaming API: same file, same
    footer, but each column becomes one ``jax.Array`` per row group, and
    each row group decodes in ONE fused compiled step fed by ONE packed
    host→device transfer.
    """

    def __init__(self, source, device: Optional[jax.Device] = None,
                 float64_policy: str = "auto", host_threads: Optional[int] = None,
                 sync_transfers: Optional[bool] = None,
                 dict_form: str = "gather"):
        """``float64_policy``: how DOUBLE columns materialize on device —
        "auto" (exact float64 on CPU; float32 on TPU, where f64 is emulated
        and lossy anyway), "float64", "float32", or "bits" (exact int64 bit
        patterns).

        ``host_threads``: size of the pool that runs arena fill jobs
        (decompression into disjoint regions) concurrently; 0/1 disables,
        None picks a default from the CPU count.  Prefetch additionally
        overlaps staging of group i+1 with device work of group i.

        ``sync_transfers``: block until each group's arena transfer lands
        before dispatching the decode.  Default on (None → env
        ``PFTPU_SYNC_TRANSFERS``, default "1"): on tunnelled TPU links,
        letting transfers queue asynchronously contends with the host
        staging threads and *triples* staging latency — one outstanding
        transfer at a time is the faster pipeline.  Set to False on
        locally-attached devices to overlap transfer with staging.

        ``dict_form``: how flat dictionary-encoded columns materialize —
        "gather" (dense decoded values; strings as (n, max_len) byte
        matrices) or "index" (the index stream as ``values``, packed to
        the narrowest dtype the pool size allows, plus the pool itself in
        ``DeviceColumn.dict_ref`` — what host row cursors want: fetches
        shrink 2-8x and values convert once per distinct, not per cell).
        Plain/mixed string chunks and repeated leaves always gather;
        DOUBLE under a lossy float policy gathers too (the device
        conversion semantics cannot be reproduced from the host pool).
        """
        _require_x64()
        if dict_form not in ("gather", "index"):
            raise ValueError(f"bad dict_form {dict_form!r}")
        self._dict_form = dict_form
        owns_reader = not isinstance(source, ParquetFileReader)
        self.reader = source if not owns_reader else ParquetFileReader(source)
        opts = getattr(self.reader, "options", None)
        if opts is not None and opts.verify_crc and not opts.salvage:
            # the robustness contract lives at THIS boundary, not just the
            # API wrapper above it: the fused device path has no CRC
            # check, so silently accepting such a reader would skip the
            # verification it was configured for.  With salvage=True the
            # group decode is DELEGATED to the host engine (below), which
            # does run the CRC check — so the combination is honored.
            from ..errors import UnsupportedFeatureError

            if owns_reader:
                self.reader.close()
            raise UnsupportedFeatureError(
                "ReaderOptions.verify_crc is a host-engine feature; the "
                "TPU engine cannot honor it — decode via the host engine "
                "instead"
            )
        # salvage IS honored — by delegating each group's decode to the
        # host salvage engine and shipping the surviving arrays (the
        # quarantine decision must be byte-deterministic and identical
        # across faces, which only one detector can guarantee); the
        # fused device decode never runs on a salvage reader
        self._salvage = bool(opts is not None and opts.salvage)
        # per-group unit reports (salvage only): each salvage decode
        # lands its own SalvageReport here, keyed by group index, for
        # consumers that fold per-unit quarantines (the DataLoader's
        # merge protocol); the reader's shared report still accumulates
        # everything for close()-time quarantine-map recording
        self._unit_salvage: Dict[int, object] = {}
        self._unit_merged: set = set()
        if self._salvage:
            trace.decision("salvage.device_host_decode", {
                "path": getattr(self.reader.source, "name", None),
                "why": "salvage pins the quarantine decision to the host "
                       "decoder; device groups ship host-salvaged arrays",
            })
        self.device = device
        if float64_policy not in ("auto", "float64", "float32", "bits"):
            raise ValueError(f"bad float64_policy {float64_policy!r}")
        if float64_policy == "auto":
            if _platform_is_tpu():
                if any(
                    d.physical_type == Type.DOUBLE
                    for d in self.reader.schema.columns
                ):
                    import warnings

                    warnings.warn(
                        "float64_policy='auto' decodes DOUBLE columns as "
                        "float32 on TPU (the reference returns exact "
                        "doubles); pass float64_policy='bits' for "
                        "bit-exact int64 bit patterns or 'float64' for "
                        "x64 doubles",
                        stacklevel=2,
                    )
                float64_policy = "float32"
            else:
                float64_policy = "float64"
        self.float64_policy = float64_policy
        self._f64mode = {"float32": "f32", "bits": "bits", "float64": "f64"}[
            float64_policy
        ]
        import os as _os

        if sync_transfers is None:
            sync_transfers = _os.environ.get("PFTPU_SYNC_TRANSFERS", "1") != "0"
        self.sync_transfers = sync_transfers
        # Chunked arena shipping: overlap fill (host CPU) with transfer
        # (DMA) inside a single row group — the only overlap available to
        # single-group files, where cross-group pipelining has nothing to
        # hide behind.  PFTPU_CHUNKED_SHIP=0/1 overrides the TPU default.
        ch_env = _os.environ.get("PFTPU_CHUNKED_SHIP", "")
        if ch_env in ("0", "1"):
            self._chunked_ship = ch_env == "1"
        else:
            self._chunked_ship = _platform_is_tpu()
        # Pallas expansion for uniform-bit-width streams.  The lane-gather
        # kernel formulation compiles under Mosaic for every width 1..32
        # (``rle_kernel.lane_compiled`` is total since round 3) — default
        # ON on a real TPU.  PFTPU_PALLAS=0 disables; PFTPU_PALLAS=1
        # forces it everywhere via interpret mode (tests).
        pl_env = _os.environ.get("PFTPU_PALLAS", "")
        if pl_env == "1":
            self._pl_enabled = True
            self._pl_interp = True
        elif pl_env == "0":
            self._pl_enabled = False
            self._pl_interp = False
        else:
            self._pl_enabled = _platform_is_tpu()
            self._pl_interp = False
        if host_threads is None:
            host_threads = min(8, _os.cpu_count() or 1)
        self._fill_pool = (
            ThreadPoolExecutor(max_workers=host_threads,
                               thread_name_prefix="pftpu-fill")
            if host_threads and host_threads > 1
            else None
        )
        # Arena byte budget per decode launch.  Groups whose footer
        # estimate exceeds it split into multiple launches
        # (read_row_group chunking) instead of erroring.  The default is
        # an HBM WORKING-SET budget, not the int32 plan ceiling:
        # byte-granular decode on TPU pads narrow (n, width) reshapes to
        # (8,128) tiles, so a launch transiently needs up to ~64x its
        # arena bytes (measured: a 64-bit PLAIN column costs ~512 B per
        # value through the u8→u32→i64 bitcast chain).  64 MiB bounds
        # that at ~4 GB of HBM while keeping every bench config a single
        # launch.  PFTPU_ARENA_CAP (bytes) overrides either way; the
        # absolute int32 ceiling stays as the per-launch safety net.
        # One definition shared with the cost model (cost.arena_cap), so
        # "auto"'s splittability prediction can never drift from the cap
        # the launches actually use.
        from .cost import arena_cap

        self._arena_cap = arena_cap()
        self._forced: set = set()   # columns pinned to the host path (per file)
        self._hwm_state: Dict[tuple, int] = {}
        # string-dictionary pools are keyed by (sha256(content), cap, len).
        # Staging reuses any already-shipped key whose buckets dominate the
        # requested ones, so buckets growing across row groups do not pile
        # up duplicate device pools (and no eviction is needed — an evicted
        # key could still be referenced by a concurrently staged group).
        self._sdict_meta: Dict[bytes, tuple] = {}   # digest → (num, max_len)
        self._sdict_host: Dict[tuple, tuple] = {}   # key → (rows, lens)
        self._sdict_dev: Dict[tuple, tuple] = {}    # key → (rows_dev, lens_dev)
        # mesh placement ships dictionary pools per TARGET device: the
        # default-device dict above stays authoritative for every
        # single-device path; explicitly-placed groups resolve through
        # their device's own dict (docs/multichip.md)
        self._sdict_dev_mesh: Dict[object, Dict[tuple, tuple]] = {}
        self._lock = threading.Lock()
        # concurrent stage workers grow the shape buckets in whatever
        # order the pool schedules groups — padded widths would vary run
        # to run (values never do).  Seeding the footer-derivable
        # buckets to their file-wide maxima BEFORE any staging makes
        # every size-driven bucket order-independent (docs/perf.md)
        if int(_os.environ.get("PFTPU_STAGE_WORKERS", "1") or "1") > 1:
            self._preseed_buckets()
        else:
            # the mesh scheduler stages k groups concurrently (stage
            # pool sized to devices) — same order-nondeterminism, same
            # preseed remedy (docs/multichip.md)
            from ..parallel import mesh as _mesh

            if _mesh.mesh_enabled():
                self._preseed_buckets()
        # eager exec-cache preload (docs/perf.md): deserialize persisted
        # executables on a daemon thread NOW, so the per-entry wall hides
        # behind the file opens/staging ahead of the first dispatch
        from . import exec_cache as _ec

        _ec.preload_async()

    # -- bucket bookkeeping -------------------------------------------------

    def _hwm(self, key: tuple, n: int, minimum: int = 16) -> int:
        """Monotone shape bucket: never shrinks, so later row groups reuse
        earlier compiled programs."""
        b = _bucket15(max(n, 1), minimum)
        with self._lock:
            prev = self._hwm_state.get(key, 0)
            if b < prev:
                b = prev
            else:
                self._hwm_state[key] = b
        return b

    def _sdict_dev_for(self, device=None) -> Dict[tuple, tuple]:
        """The device-resident dictionary-pool dict for ``device``
        (None = the reader's default device).  Mesh-placed groups must
        resolve extras against THEIR chip: a pool shipped to device 0
        does not exist on device 1 (docs/multichip.md)."""
        if device is None:
            return self._sdict_dev
        with self._lock:
            d = self._sdict_dev_mesh.get(device)
            if d is None:
                d = self._sdict_dev_mesh[device] = {}
            return d

    def _host_extra(self, key: tuple):
        """The host (rows, lens) matrices for dictionary key ``key``,
        reconstructing from any device copy when the host copy was
        already dropped (a reader that shipped single-device first and
        mesh-places later — one D2H fetch, then cached again)."""
        with self._lock:
            pair = self._sdict_host.get(key)
            if pair is not None:
                return pair
            for d in (self._sdict_dev, *self._sdict_dev_mesh.values()):
                dev_pair = d.get(key)
                if dev_pair is not None:
                    pair = (np.asarray(dev_pair[0]), np.asarray(dev_pair[1]))
                    self._sdict_host[key] = pair
                    return pair
        raise KeyError(key)

    def _preseed_buckets(self) -> None:
        """Seed the footer-derivable shape buckets to their file-wide
        maxima (``PFTPU_STAGE_WORKERS > 1``; docs/perf.md).

        With one stage worker, buckets grow monotonically in group order
        — deterministic.  With k>1 the growth order follows pool
        scheduling, so a group staged before/after a bigger sibling gets
        different padded widths run to run.  Seeding each SIZE-driven
        bucket to a footer bound that dominates every group's need makes
        those widths order-independent:

        * ``nexp`` — the value-expansion count is the chunk's NON-NULL
          count: exact when the footer statistics carry a
          ``null_count`` (``num_values - null_count``), else bounded by
          ``num_values`` (non-nulls ≤ values — null-heavy optional
          columns without stats over-pad toward the value count);
        * ``pages`` — page-table rows are at most the OffsetIndex's page
          count (pages with values ≤ pages);
        * ``mb`` — DELTA miniblocks are at most ``ceil(n / 32) + 8``
          (spec: 128-value blocks × 4 miniblocks, plus header slack);
        * ``arena`` — staged payloads are at most the footer's
          ``total_uncompressed_size`` total (which includes page-header
          bytes the arena never stores), plus the Pallas lead/tail.

        CONTENT-driven buckets (string byte lengths, dictionary entry
        counts, RLE run tables — the latter slab-internal) are not
        derivable from the footer and still grow by high-water mark;
        returned column shapes stay byte-stable whenever those widths
        are uniform across a file's groups (the pinned k=2 test's
        shape).  Overshoot is bounded: the seeds are the same maxima the
        buckets converge to after one full pass anyway."""
        per_nexp: Dict[str, int] = {}
        per_pages: Dict[str, int] = {}
        per_mb: Dict[str, int] = {}
        arena_max = 0
        for rg in self.reader.row_groups:
            group_bytes = 0
            for chunk in rg.columns or []:
                meta = chunk.meta_data
                if meta is None or not meta.path_in_schema:
                    continue
                path = tuple(meta.path_in_schema)
                name = path[0] if len(path) == 1 else ".".join(path)
                nv = int(meta.num_values or 0)
                nn = nv
                st = meta.statistics
                if st is not None and st.null_count is not None and \
                        0 <= int(st.null_count) <= nv:
                    nn = nv - int(st.null_count)
                per_nexp[name] = max(per_nexp.get(name, 0), nn)
                group_bytes += int(meta.total_uncompressed_size or 0)
                if Encoding.DELTA_BINARY_PACKED in (meta.encodings or []):
                    per_mb[name] = max(
                        per_mb.get(name, 0), -(-nv // 32) + 8
                    )
                try:
                    oi = self.reader.read_offset_index(chunk)
                except (OSError, MemoryError):
                    raise
                except Exception:
                    oi = None  # unreadable index: that bucket stays HWM
                if oi is not None and oi.page_locations:
                    per_pages[name] = max(
                        per_pages.get(name, 0), len(oi.page_locations)
                    )
            arena_max = max(arena_max, group_bytes)
        for name, nv in per_nexp.items():
            self._hwm(("nexp", name), nv)
        for name, np_ in per_pages.items():
            self._hwm(("pages", name), np_, minimum=4)
        for name, mb in per_mb.items():
            self._hwm(("mb", name), mb, minimum=4)
        if arena_max:
            lead = plk.ARENA_LEAD if self._pl_enabled else 0
            tail = plk.ARENA_TAIL if self._pl_enabled else 8
            self._hwm(("arena",), arena_max + lead + tail, minimum=1 << 16)

    def _string_dict_key(self, arena, off, size, name):
        """Content-keyed string dictionary pool: build (or reuse) the padded
        host matrices and return (cache_key, cap, max_len)."""
        import hashlib

        content = arena[off : off + size].tobytes()
        digest = hashlib.sha256(content).digest()
        with self._lock:
            meta = self._sdict_meta.get(digest)
        if meta is None:
            col, _ = decode_plain(
                content, _count_plain_strings(content), Type.BYTE_ARRAY
            )
            num = len(col)
            max_len_raw = max(int(col.lengths().max()) if num else 1, 1)
            with self._lock:
                if len(self._sdict_meta) >= 256:  # bounded metadata cache
                    self._sdict_meta.pop(next(iter(self._sdict_meta)))
                self._sdict_meta[digest] = (num, max_len_raw)
        else:
            col = None
            num, max_len_raw = meta
        cap = self._hwm(("sdict_cap", name), num)
        max_len = self._hwm(("sdict_len", name), max_len_raw)
        with self._lock:
            # reuse the smallest already-built pool that dominates the
            # requested buckets (same content at a grown bucket otherwise
            # duplicates the pool on device)
            pool_keys = list(self._sdict_dev) + list(self._sdict_host)
            for d in self._sdict_dev_mesh.values():
                pool_keys.extend(d)
            candidates = [
                k
                for k in pool_keys
                if k[0] == digest and k[1] >= cap and k[2] >= max_len
            ]
        if candidates:
            key = min(candidates, key=lambda k: (k[1], k[2]))
            return key, key[1], key[2]
        key = (digest, cap, max_len)
        if col is None:
            col, _ = decode_plain(
                content, _count_plain_strings(content), Type.BYTE_ARRAY
            )
        rows, lens, _ = _padded_rows(col, pad_len=max_len, pad_rows=cap)
        with self._lock:
            self._sdict_host[key] = (rows, lens)
        return key, cap, max_len

    # -- public -------------------------------------------------------------

    @property
    def metadata(self):
        return self.reader.metadata

    @property
    def num_row_groups(self) -> int:
        return len(self.reader.row_groups)

    def close(self):
        if self._fill_pool is not None:
            self._fill_pool.shutdown(wait=False)
        self.reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _group_byte_estimate(self, rg, want=None) -> int:
        """Footer estimate of a group's arena demand: total decompressed
        bytes of its (selected) chunks."""
        return sum(
            int(c.meta_data.total_uncompressed_size or 0)
            for c in rg.columns or []
            if not want or c.meta_data.path_in_schema[0] in want
        )

    def read_row_group(
        self, index: int, columns: Optional[Sequence[str]] = None,
        out_perm=None,
    ) -> Dict[str, DeviceColumn]:
        """``out_perm`` (int32, one entry per row) fuses an output row
        permutation into the decode executable — every column returns as
        ``x[perm]`` at the cost of a reordered output write, not a
        separate device pass.  Oversized (multi-launch) groups apply it
        as one follow-up gather per column instead; repeated columns
        reject it."""
        if self._salvage:
            return self._read_row_group_salvage(index, columns, out_perm)
        rg = self.reader.row_groups[index]
        want = set(columns) if columns else None
        if self._group_byte_estimate(rg, want) > self._arena_cap:
            # oversized group: split into multiple launches instead of
            # erroring (the reference streams page-at-a-time with no
            # group-size ceiling at all, ParquetReader.java:182-194)
            out = self._read_row_group_chunked(rg, index, want)
            if out_perm is not None:
                out = _permuted_columns(out, out_perm)
            return out
        sg = self._stage_row_group(index, columns)
        return self._launch(sg, out_perm=out_perm)

    def _read_row_group_salvage(self, index: int, columns, out_perm=None,
                                row_ranges=None):
        """Salvage decode of one group on the DEVICE face.

        The quarantine decision must be byte-deterministic and identical
        to the host face's (the differential fuzz contract), which only
        one detector can guarantee — so the unit decodes through the
        host salvage engine (all four tiers: page-null, row-mask, chunk,
        quarantine map; accounting lands in ``reader.salvage_report``)
        and the SURVIVING arrays ship to device as ``DeviceColumn``s.
        Chunk-quarantined columns are simply absent from the returned
        dict, exactly as they are absent from the host
        ``RowGroupBatch``.  This is a recovery path, not a fast path:
        it pays host decode per unit by design.

        With ``row_ranges`` the host read is the RANGED salvage path
        (clean chunks keep their I/O pruning; see
        ``_read_row_group_ranges_salvage``) and the return value is
        ``(columns_dict, covered)`` instead of the bare dict; a row
        permutation cannot combine with a partial cover."""
        from ..errors import UnsupportedFeatureError
        from ..format.file_read import SalvageReport

        if row_ranges is not None and out_perm is not None:
            raise UnsupportedFeatureError(
                "a row permutation cannot combine with a ranged salvage "
                "read (the perm indexes whole-group rows)"
            )
        want = set(columns) if columns else None
        unit_rep = SalvageReport()
        covered = None
        with trace.span("stage", attrs={
            "file": getattr(self.reader.source, "name", None),
            "row_group": index,
        }):
            if row_ranges is None:
                batch = self.reader.read_row_group(
                    index, want, report=unit_rep
                )
            else:
                batch, covered = self.reader.read_row_group_ranges(
                    index, row_ranges, want, report=unit_rep
                )
        # the shared report still sees everything (close() records it
        # into the quarantine map); the per-unit copy is what consumers
        # with a merge protocol take.  The merge is once-per-group:
        # re-decoding a group is deterministic and must not double its
        # losses on the shared books (the host reader's idempotency
        # contract, kept at this boundary too).
        if self.reader.salvage_report is not None and \
                index not in self._unit_merged:
            self.reader.salvage_report.merge_in(unit_rep)
            self._unit_merged.add(index)
        self._unit_salvage[index] = unit_rep
        # stage every surviving column's host arrays first, then ship
        # them in ONE device_put call — the salvage recovery path keeps
        # the engine's one-transfer discipline instead of paying a
        # launch-queue round trip per column array
        staged: list = []   # (name, desc, v_idx, m_idx, l_idx)
        host_arrays: list = []

        def _put(a) -> int:
            host_arrays.append(a)
            return len(host_arrays) - 1

        for cb in batch.columns:
            desc = cb.descriptor
            name = desc.path[0] if len(desc.path) == 1 else ".".join(desc.path)
            if desc.max_repetition_level > 0:
                raise UnsupportedFeatureError(
                    "salvage on the device face supports flat columns "
                    f"only; project the repeated column {name!r} away or "
                    "use the host engine"
                )
            dense, mask = cb.dense()
            m_idx = -1 if mask is None else _put(np.asarray(mask))
            if isinstance(dense, ByteArrayColumn):
                rows, lens, _ = _padded_rows(dense)
                staged.append((name, desc, _put(rows), m_idx, _put(lens)))
                continue
            v = np.asarray(dense)
            if desc.physical_type == Type.DOUBLE:
                if self._f64mode == "bits":
                    v = v.view(np.int64)
                elif self._f64mode == "f32":
                    v = v.astype(np.float32)
            staged.append((name, desc, _put(v), m_idx, -1))
        shipped = jax.device_put(host_arrays, self.device)
        out: Dict[str, DeviceColumn] = {}
        for name, desc, v_idx, m_idx, l_idx in staged:
            out[name] = DeviceColumn(
                desc, shipped[v_idx],
                shipped[m_idx] if m_idx >= 0 else None,
                shipped[l_idx] if l_idx >= 0 else None,
            )
        if out_perm is not None and not unit_rep.geometry_damaged(index):
            # a geometry-damaged group has fewer rows (or columns) than
            # the footer promised: the caller's whole-rows permutation no
            # longer indexes it.  Consumers with a perm (the DataLoader)
            # quarantine such units wholesale — returning them unpermuted
            # is safe, applying a stale perm would be an index error.
            out = _permuted_columns(out, out_perm)
        if row_ranges is not None:
            return out, covered
        return out

    def take_unit_report(self, index: int):
        """Pop the per-unit :class:`SalvageReport` the last salvage
        decode of group ``index`` produced (None in strict mode or when
        the group has not decoded).  The pipeline decodes ahead on its
        stage worker, but a group's report is stashed before the group
        yields, so taking it right after consuming the group is safe."""
        return self._unit_salvage.pop(index, None)

    def _launch_pipelined(self, stage_calls):
        """Run several (args, kwargs) ``_stage_row_group`` calls as a
        2-stage pipeline: stage i+1 on a worker while launch i ships and
        decodes on this thread (the chunk paths' sibling of the group
        iterator's stage‖ship‖decode).  Staging is forced unchunked so
        only one thread issues transfers at a time.  Yields each
        launch's column dict in order."""
        if len(stage_calls) == 1:
            args, kwargs = stage_calls[0]
            yield self._launch(
                self._stage_row_group(*args, chunked=False, **kwargs)
            )
            return
        with ThreadPoolExecutor(max_workers=1,
                                thread_name_prefix="pftpu-chunkstage") as sp:
            pending = deque()
            for args, kwargs in stage_calls:
                pending.append(sp.submit(
                    self._stage_row_group, *args, chunked=False, **kwargs
                ))
                # keep at most one staged group in flight beyond the one
                # being launched (each pins a host arena)
                while len(pending) > 1:
                    yield self._launch(pending.popleft().result())
            while pending:
                yield self._launch(pending.popleft().result())

    def _read_row_group_chunked(self, rg, index: int, want) -> Dict[str, DeviceColumn]:
        """Decode one oversized row group in several launches: greedy
        COLUMN bins under the cap first; a single field whose chunks
        alone exceed the cap row-splits on the common page grid."""
        fields: List[str] = []
        field_bytes: Dict[str, int] = {}
        for c in rg.columns or []:
            top = c.meta_data.path_in_schema[0]
            if want and top not in want:
                continue
            if top not in field_bytes:
                fields.append(top)
                field_bytes[top] = 0
            field_bytes[top] += int(c.meta_data.total_uncompressed_size or 0)
        out: Dict[str, DeviceColumn] = {}
        bins: List[List[str]] = []
        splits: List[str] = []  # fields that row-split (decoded after bins)
        bin_names: List[str] = []
        bin_total = 0
        for f in fields:
            fb = field_bytes[f]
            if fb > self._arena_cap:
                splits.append(f)
                continue
            if bin_total + fb > self._arena_cap and bin_names:
                bins.append(bin_names)
                bin_names = []
                bin_total = 0
            bin_names.append(f)
            bin_total += fb
        if bin_names:
            bins.append(bin_names)
        for res in self._launch_pipelined(
            [((index, list(b)), {}) for b in bins]
        ):
            out.update(res)
        for f in splits:
            out.update(
                self._read_field_row_split(rg, index, f, field_bytes[f])
            )
        return out

    def _read_field_row_split(self, rg, index: int, field: str,
                              field_bytes: int) -> Dict[str, DeviceColumn]:
        """One field bigger than the arena cap: decode page-aligned row
        segments in successive launches and concatenate on device (flat
        columns directly; repeated leaves pack their dense value streams
        by traced-count scatter).  Needs the OffsetIndex to find
        page-aligned split points shared by the field's leaves — which
        also guarantees segments never split a record."""
        n = int(rg.num_rows or 0)
        chunks = [
            c for c in rg.columns or []
            if c.meta_data.path_in_schema[0] == field
        ]
        missing_oi = any(
            (oi := self.reader.read_offset_index(c)) is None
            or not oi.page_locations
            for c in chunks
        )
        subs = []
        if not missing_oi:
            per_row = field_bytes / max(n, 1)
            subs = self._split_covered([(0, n)], per_row, chunks)
        if missing_oi or len(subs) <= 1:
            # unsplittable over-cap field (no OffsetIndex, or no page
            # boundary lands under the cap): decode the whole column on
            # the HOST path in one launch instead of refusing.  The
            # reference streams page-at-a-time with no size ceiling
            # (ParquetReader.java:182-194) — the device engine must
            # never refuse a file shape the host engine reads fine.
            # Host-decoded columns ship dense (no (8,128)-tile padding
            # blowup), so the arena cap does not apply; only the 2 GiB
            # int32 plan ceiling still guards the launch.
            return self._read_field_host_fallback(
                index, field, field_bytes,
                "no OffsetIndex" if missing_oi
                else "no page boundary under the cap",
            )
        parts: Dict[str, List[DeviceColumn]] = {}
        calls = [
            ((index, [field]), {"covered": sub, "group_rows": n})
            for sub in subs
        ]
        for res in self._launch_pipelined(calls):
            for k, v in res.items():
                parts.setdefault(k, []).append(v)
        return {k: _concat_device_columns(v) for k, v in parts.items()}

    def _read_field_host_fallback(self, index: int, field: str,
                                  field_bytes: int, why: str
                                  ) -> Dict[str, DeviceColumn]:
        """Graceful path for an over-cap field that cannot row-split:
        pin every leaf of the field to the host decode path (sticky per
        file, like every other ``_forced`` entry — the shape repeats in
        later row groups) and decode it in a single launch."""
        rg = self.reader.row_groups[index]
        names = set()
        for c in rg.columns or []:
            path = tuple(c.meta_data.path_in_schema)
            if path[0] == field:
                names.add(path[0] if len(path) == 1 else ".".join(path))
        self._forced.update(names)
        trace.decision("chunk_fallback", {
            "row_group": index,
            "field": field,
            "decompressed_bytes": int(field_bytes),
            "arena_cap": int(self._arena_cap),
            "why": why,
            "action": "whole-column host decode (raise PFTPU_ARENA_CAP "
                      "to decode on device in one launch)",
        })
        sg = self._stage_row_group(index, [field])
        return self._launch(sg)

    def read_row_group_ranges(
        self, index: int, row_ranges, columns: Optional[Sequence[str]] = None
    ):
        """Selective device decode: only pages whose rows intersect
        ``row_ranges`` are read from disk, staged, shipped, and decoded
        (pair with ``Predicate.row_ranges``).  Returns
        ``(columns_dict, covered)`` where ``covered`` lists the
        page-aligned row ranges the decoded rows correspond to; falls
        back to the whole group when any chunk lacks an OffsetIndex."""
        from ..batch.predicate import normalize_ranges

        rg = self.reader.row_groups[index]
        n = int(rg.num_rows or 0)
        if not normalize_ranges(row_ranges, n):
            return {}, []  # predicate excluded every row
        chunk_filter = set(columns) if columns else None
        chunks = [
            c for c in rg.columns or []
            if not chunk_filter or c.meta_data.path_in_schema[0] in chunk_filter
        ]
        if not chunks:
            return self.read_row_group(index, columns), [(0, n)] if n else []
        if self._salvage:
            # ranged salvage: the HOST engine computes the cover itself
            # (defensively — a damaged OffsetIndex falls back to the
            # whole group), keeps I/O pruning for clean chunks and
            # widens only damaged ones; the survivors ship exactly like
            # the whole-group salvage face
            return self._read_row_group_salvage(
                index, columns, row_ranges=row_ranges
            )
        covered = self.reader.page_cover(index, row_ranges, chunks)
        if covered == []:
            return {}, []
        if covered is None or covered == [(0, n)]:
            return self.read_row_group(index, columns), [(0, n)] if n else []
        # the arena cap binds ranged reads too (HBM working-set bound,
        # same as read_row_group): oversized covers decode in several
        # launches and concatenate (repeated leaves pack by
        # traced-count scatter, see _concat_repeated_parts)
        est = self._group_byte_estimate(rg, chunk_filter)
        cov_rows = sum(b - a for a, b in covered)
        per_row = est / max(n, 1)
        if cov_rows * per_row > self._arena_cap:
            parts: Dict[str, List[DeviceColumn]] = {}
            calls = [
                ((index, columns), {"covered": sub, "group_rows": n})
                for sub in self._split_covered(covered, per_row, chunks)
            ]
            for res in self._launch_pipelined(calls):
                for k, v in res.items():
                    parts.setdefault(k, []).append(v)
            return (
                {k: _concat_device_columns(v) for k, v in parts.items()},
                covered,
            )
        sg = self._stage_row_group(index, columns, covered=covered, group_rows=n)
        return self._launch(sg), covered

    def _split_covered(self, covered, per_row: float, chunks):
        """Partition page-aligned covered ranges into consecutive
        sublists each estimated under the arena cap; a single range too
        big on its own splits further on the page-start grid shared by
        the selected chunks (the OffsetIndexes exist — ``page_cover``
        returned non-None)."""
        cap_rows = max(int(self._arena_cap / max(per_row, 1e-9)), 1)
        grid = None
        ranges: List[tuple] = []
        for a, b in covered:
            if b - a <= cap_rows:
                ranges.append((a, b))
                continue
            if grid is None:
                sets = []
                for c in chunks:
                    oi = self.reader.read_offset_index(c)
                    sets.append({
                        int(pl.first_row_index or 0)
                        for pl in (oi.page_locations if oi else [])
                    })
                grid = sorted(set.intersection(*sets)) if sets else []
            cuts = [p for p in grid if a < p < b]
            start = a
            prev = None
            for p in cuts + [b]:
                if p - start > cap_rows and prev is not None and prev > start:
                    ranges.append((start, prev))
                    start = prev
                prev = p
            if start < b:
                ranges.append((start, b))
        subs: List[list] = []
        acc: list = []
        acc_rows = 0
        for a, b in ranges:
            if acc and acc_rows + (b - a) > cap_rows:
                subs.append(acc)
                acc = []
                acc_rows = 0
            acc.append((a, b))
            acc_rows += b - a
        if acc:
            subs.append(acc)
        return subs

    def iter_row_groups(self, columns: Optional[Sequence[str]] = None,
                        prefetch: bool = True, predicate=None,
                        indices: Optional[Sequence[int]] = None):
        """Decode every row group, pipelining the three stages: host
        staging (read + decompress + plan) of group i+1 AND its device
        transfer both run in the background while the device computes the
        fused decode of group i and the caller consumes it.  One transfer
        is in flight at a time (``sync_transfers`` semantics preserved —
        the background task stages, then ships, sequentially).

        ``predicate`` (see ``batch.predicate.col``) skips row groups whose
        footer statistics prove no row can match — before any page is
        read, staged, or shipped.  ``indices`` restricts/reorders the
        groups visited (e.g. resuming a row cursor mid-file); it composes
        with ``predicate`` by intersection, preserving ``indices`` order."""
        if predicate is not None:
            keep = set(predicate.row_groups(self.reader))
            base = indices if indices is not None else range(self.num_row_groups)
            indices = [i for i in base if i in keep]
        elif indices is not None:
            indices = list(indices)
        else:
            indices = list(range(self.num_row_groups))
        yield from iter_dataset_row_groups(
            [(self, i) for i in indices], columns, prefetch
        )

    # -- staging ------------------------------------------------------------

    def _stage_row_group(self, index: int, columns, covered=None,
                         group_rows: int = 0, chunked=None,
                         compute=None, device=None) -> _StagedGroup:
        src = getattr(self.reader.source, "name", None)
        with trace.span("stage", attrs={"file": src, "row_group": index},
                        observe="engine.stage_seconds"):
            sg = self._stage_row_group_untraced(
                index, columns, covered, group_rows, chunked=chunked,
                compute=compute, device=device,
            )
        sg.source = src
        sg.group_index = index
        return sg

    def _stage_row_group_untraced(self, index: int, columns, covered=None,
                                  group_rows: int = 0, chunked=None,
                                  compute=None, device=None) -> _StagedGroup:
        rg = self.reader.row_groups[index]
        want = set(columns) if columns else None
        if compute is not None and want is not None:
            # predicate/aggregate columns must stage (and decode) even
            # when outside the projection; the cplan's ship set still
            # honors the projection
            want = want | {
                c.split(".")[0] for c in compute[0].columns_needed()
            }
        work = []
        for chunk in rg.columns or []:
            path = tuple(chunk.meta_data.path_in_schema)
            # projection filters by top-level field name (reference
            # ParquetReader.java:126-128); result keys use the full dotted
            # path so sibling leaves under one group don't collide
            if want and path[0] not in want:
                continue
            desc = self.reader.schema.column(path)
            name = path[0] if len(path) == 1 else ".".join(path)
            work.append((name, chunk, desc))
        while True:
            try:
                return self._try_stage(
                    rg, work, self._forced,
                    covered=covered, group_rows=group_rows, chunked=chunked,
                    compute=compute, device=device,
                )
            except _ForceHost as e:
                # sticky per file: a column that needed the host path once
                # (e.g. >32-bit delta range) skips the device attempt in
                # every later row group instead of staging the group twice
                self._forced.update(e.keys)

    def _build_plan5(self, key: tuple, arena, streams, total: int):
        """``bitops.plan5_from_streams`` padded to the column's sticky
        HWM bucket, growing the bucket when the run count exceeds it
        (the overflow carries the exact count — at most one retry).
        Returns ``(flat int32 plan, pad_runs)``."""
        need = 16
        while True:
            pad = self._hwm(key, need)
            try:
                plan, _used = bitops.plan5_from_streams(
                    arena, streams, total, pad
                )
                return plan, pad
            except bitops.PlanPadExceeded as e:
                need = e.needed

    def _pallas_plan(self, plan: np.ndarray, n_runs: int, count: int,
                     bw: int, slabb: _I32Builder):
        """Build the (bw, span_off, n_tiles, interpret) Pallas plan for a
        uniform-width stream, or () when gated off / not worthwhile."""
        if not self._pl_enabled or bw == 0 or bw > 32 or count < plk.TILE:
            return ()
        if not self._pl_interp and not plk.lane_compiled(bw):
            # compiled Mosaic supports only the lane-gather kernel
            return ()
        if count > plk.PL_MAX_VALUES:
            # tile spans ride scalar prefetch (SMEM, 1 MiB per program):
            # bound the tile count
            return ()
        out_end = plan.reshape(5, n_runs)[0]
        tl, th = plk.tile_spans_padded(out_end, count)
        hbm_plan = 0
        if n_runs > plk.PL_MAX_RUNS:
            # the 5-row plan no longer fits scalar prefetch (gate on the
            # padded run count — what actually ships, hwm-sticky by
            # design): switch to the HBM-plan kernel, where each tile
            # DMAs only its own run window into SMEM.  Bail out only on
            # plans past the (generous) size cap or with a tile whose
            # aligned window exceeds the SMEM scratch (possible only via
            # zero-length runs piling onto one tile).
            if n_runs > plk.PL_MAX_RUNS_HBM:
                return ()
            if plk.max_aligned_span(tl, th) > plk.PL_RUN_WIN:
                return ()
            hbm_plan = 1
        span_off = slabb.add(np.concatenate([tl, th]))
        return (bw, span_off, len(tl), self._pl_interp, hbm_plan)

    def _try_stage(self, rg, work, forced, covered=None,
                   group_rows: int = 0, chunked=None,
                   compute=None, device=None) -> _StagedGroup:
        arena_b = _ArenaBuilder(plk.ARENA_LEAD if self._pl_enabled else 0)
        stages = []
        for name, chunk, desc in work:
            raw_pages = (
                self.reader.read_raw_column_chunk_ranges(
                    chunk, covered, group_rows
                )
                if covered is not None
                else None
            )
            if name in forced:
                stages.append(
                    _HostStage(name, chunk, desc, self, arena_b,
                               covered=covered, group_rows=group_rows,
                               raw_pages=raw_pages)
                )
                continue
            try:
                stages.append(
                    _DevStage(name, chunk, desc, self.reader, arena_b,
                              raw_pages=raw_pages)
                )
            except _Fallback:
                # reuse the already-fetched pages — no second disk read
                stages.append(
                    _HostStage(name, chunk, desc, self, arena_b,
                               covered=covered, group_rows=group_rows,
                               raw_pages=raw_pages)
                )
        if arena_b.size >= (1 << 31) - (1 << 20):
            # per-LAUNCH safety net (int32 device plans), normally never
            # hit: oversized groups split into multiple launches first
            # (read_row_group chunking).  Reachable only when padding
            # inflates one launch far past its footer estimate.
            raise ValueError(
                f"one decode launch stages {arena_b.size} bytes, past the "
                "2 GiB int32 plan ceiling — lower PFTPU_ARENA_CAP so the "
                "group splits into more launches, or use the host "
                "ParquetFileReader"
            )
        tail = plk.ARENA_TAIL if self._pl_enabled else 8
        cap = checked_alloc_size(
            self._hwm(("arena",), arena_b.size + tail, minimum=1 << 16),
            "host staging arena",
        )
        arena = np.zeros(cap, dtype=np.uint8)
        parts = None
        if chunked is None:
            chunked = self._chunked_ship
        if chunked and cap > _SHIP_CHUNK:
            # pipeline the arena fill with its own transfer: each fixed
            # chunk is device_put (async) the moment its fill jobs are
            # done, so decompress/copy of chunk c+1 overlaps the DMA of
            # chunk c.  Chunk boundaries depend only on the bucketed cap,
            # keeping the fused-program shape cache warm.  (If a finish()
            # below raises _ForceHost the shipped chunks are wasted — a
            # one-time cost per file, since forcing is sticky per column.)
            with trace.span("ship", cap, observe="engine.ship_seconds"):
                plist = []
                for s, e in arena_b.fill_chunks(
                    arena, _SHIP_CHUNK, self._fill_pool
                ):
                    if plist and self.sync_transfers:
                        # sliding window of ONE outstanding transfer: the
                        # fill of this chunk already overlapped the DMA of
                        # the previous one, and a deeper async queue
                        # trips the tunnel's burst throttle
                        jax.block_until_ready(plist[-1])
                    plist.append(jax.device_put(
                        arena[s:e],
                        device if device is not None else self.device,
                    ))
                if self.sync_transfers:
                    jax.block_until_ready(plist)
                parts = tuple(plist)
        else:
            if arena_b.inflate_bytes:
                # host inflate as its own timed span: the pipeline's
                # per-group stage task runs this concurrently with other
                # groups' transfers and decode dispatches — the timeline
                # intervals are what the overlap measurement intersects
                # (docs/multichip.md; the chunked-ship path interleaves
                # fill with its own transfer and stays inside "ship")
                with trace.span(
                    "inflate", arena_b.inflate_bytes,
                    observe="scan.inflate_seconds",
                ):
                    arena_b.fill(arena, self._fill_pool)
                trace.count("scan.inflate_bytes", arena_b.inflate_bytes)
            else:
                arena_b.fill(arena, self._fill_pool)
        slabb = _I32Builder()
        raw_specs = []
        force_keys = []
        for st in stages:
            try:
                raw_specs.append(st.finish(arena, slabb, self))
            except bitops.PlanOverflow:
                # the column's run tables cannot ride int32 device plans
                # (e.g. one bit-packed run past 2³¹ bits) — host path
                force_keys.append(st.name)
            except _ForceHost as e:
                force_keys.extend(e.keys)
        if force_keys:
            # one combined restage for every offending column (chunked
            # staging may already have shipped arena chunks; restaging
            # once bounds that waste regardless of how many columns fall)
            raise _ForceHost(*force_keys)
        # assign extras (string dictionaries) in order of first use
        extra_keys: List[tuple] = []
        new_extras: List[tuple] = []
        host_pools: dict = {}
        specs = []
        for rs in raw_specs:
            key = rs.pop("_extra_key", None)
            pool = rs.pop("_host_pool", None)
            if pool is not None:
                host_pools[rs["name"]] = pool
            if key is not None:
                if key not in extra_keys:
                    extra_keys.append(key)
                    sdict_dev = self._sdict_dev_for(device)
                    with self._lock:
                        missing = key not in sdict_dev
                    if missing:
                        rows, lens = self._host_extra(key)
                        new_extras.append((key, rows, lens))
                rs["extra_idx"] = extra_keys.index(key)
            specs.append(_ColSpec(**rs))
        slab = slabb.build(self._hwm(("slab",), slabb.n, minimum=256))
        num_rows = (
            sum(b - a for a, b in covered)
            if covered is not None
            else rg.num_rows or 0
        )
        built = None
        if compute is not None:
            # compile the pushdown compute tail against THIS staged
            # program (the dictionary-match masks and group keys read
            # the group's dictionaries straight out of the arena)
            from . import compute as _compute

            request, ship = compute
            stages_by_name = {st.name: st for st in stages}
            built = _compute.build_for_program(
                request, tuple(specs), stages_by_name, arena, num_rows
            )
            if ship is not None:
                built.cplan = built.cplan._replace(ship=tuple(
                    s.name for s in specs
                    if s.name in ship or s.name.split(".")[0] in ship
                ))
        return _StagedGroup(
            program=tuple(specs),
            arena=arena,
            slab=slab,
            descs=[d for _, _, d in work],
            extra_keys=extra_keys,
            new_extras=new_extras,
            num_rows=num_rows,
            parts=parts,
            host_pools=host_pools or None,
            compute=built,
            device=device,
        )

    # -- launch -------------------------------------------------------------

    def _ship(self, sg: _StagedGroup) -> list:
        """Transfer a staged group's arrays to the device (one transfer
        in flight at a time when ``sync_transfers``).  Arena chunks
        already shipped during staging (``sg.parts``) are not re-sent."""
        # several prefetched groups can stage the same dictionary before
        # the first of them ships it — re-check at ship time (ships are
        # serialized per device) so it crosses each link once
        target = sg.device if sg.device is not None else self.device
        sdict_dev = self._sdict_dev_for(sg.device)
        with self._lock:
            extras = [e for e in sg.new_extras if e[0] not in sdict_dev]
        ship = [] if sg.parts is not None else [sg.arena]
        ship.append(sg.slab)
        for _, rows, lens in extras:
            ship.append(rows)
            ship.append(lens)
        if sg.compute is not None:
            # dictionary-match masks of the compute tail: per-group
            # device inputs, always LAST in the ship list (the decode
            # path slices them off the tail)
            ship.extend(sg.compute.masks)
        with trace.span("ship", sum(int(a.nbytes) for a in ship),
                        attrs={"file": sg.source,
                               "row_group": sg.group_index},
                        observe="engine.ship_seconds"):
            shipped = jax.device_put(ship, target)
            if self.sync_transfers:
                jax.block_until_ready(shipped)
        if sg.parts is not None:
            shipped = [sg.parts, *shipped]
        pos = 2
        for key, _, _ in extras:
            with self._lock:
                sdict_dev[key] = (shipped[pos], shipped[pos + 1])
                if self._dict_form != "index" and sg.device is None:
                    # device copy is authoritative; index-form keeps the
                    # host copy so consumers read pools without a D2H
                    # trip, and mesh-placed groups keep it so OTHER
                    # devices can still ship the same pool
                    self._sdict_host.pop(key, None)
            pos += 2
        return shipped

    def _decode_shipped(self, sg: _StagedGroup, shipped: list,
                        out_perm=None) -> Dict[str, DeviceColumn]:
        """Dispatch the fused decode over already-shipped device buffers
        (asynchronous: returned arrays are futures until materialized).

        ``out_perm`` (int32, one entry per row) fuses an output row
        permutation into the decode executable itself — every column
        comes back as ``x[perm]`` for the price of a reordered output
        write (the loader's window shuffle).  Repeated leaves are not
        row-aligned and reject it.

        Groups staged WITH a compute tail (``sg.compute``) dispatch the
        pushdown executable instead and return a
        :class:`~parquet_floor_tpu.tpu.compute.PushdownResult`."""
        if sg.compute is not None:
            if out_perm is not None:
                from ..errors import UnsupportedFeatureError

                raise UnsupportedFeatureError(
                    "out_perm and pushdown compute cannot fuse into one "
                    "launch (a compacted output has no stable row order "
                    "to permute)"
                )
            return self._decode_shipped_compute(sg, shipped)
        first, slab_dev = shipped[0], shipped[1]
        parts = first if isinstance(first, tuple) else (first,)
        sdict_dev = self._sdict_dev_for(sg.device)
        extra_args = []
        for key in sg.extra_keys:
            rows_d, lens_d = sdict_dev[key]
            extra_args.append(rows_d)
            extra_args.append(lens_d)
        if out_perm is not None and any(
            spec.max_rep > 0 for spec in sg.program
        ):
            from ..errors import UnsupportedFeatureError

            raise UnsupportedFeatureError(
                "out_perm cannot permute repeated columns (the dense "
                "value stream is not row-aligned); project them away"
            )
        with trace.span("decode", attrs={"file": sg.source,
                                         "row_group": sg.group_index,
                                         "rows": sg.num_rows},
                        observe="engine.launch_seconds"):
            args = [*parts, slab_dev, *extra_args]
            if out_perm is not None:
                perm = out_perm
                if isinstance(perm, (list, tuple)) or (
                    getattr(perm, "dtype", None) != np.int32
                    and isinstance(perm, np.ndarray)
                ):
                    # normalize host perms to int32 (the documented
                    # contract) so one program serves every caller;
                    # device arrays pass through untouched (no D2H)
                    perm = np.ascontiguousarray(perm, dtype=np.int32)
                args.append(perm)
            outs = _run_fused(
                sg.program, len(parts), args, out_perm is not None,
                device=sg.device if sg.device is not None else self.device,
            )
        result: Dict[str, DeviceColumn] = {}
        for spec, desc, (vals, mask, lens, defs, reps) in zip(
            sg.program, sg.descs, outs
        ):
            dc = DeviceColumn(desc, vals, mask, lens, defs, reps)
            dc.dict_ref = self._dict_ref_for(spec, sg)
            result[spec.name] = dc
        return result

    def _dict_ref_for(self, spec: _ColSpec, sg: _StagedGroup):
        """The stable pool reference of an index-form dictionary column
        (None for every other kind).  The engine's content key (digest,
        cap, max_len) rides along as the STABLE cache identity —
        consumers must not key pool caches by id() (ids are reused
        after GC)."""
        if spec.kind == "dict_idx":
            key = sg.extra_keys[spec.extra_idx]
            with self._lock:
                host_pool = self._sdict_host.get(key)
            return (
                ("host_str", key, *host_pool)
                if host_pool is not None
                else ("dev", key, *self._sdict_dev_for(sg.device)[key])
            )
        if spec.kind == "dict_idx_num":
            return ("host", None, sg.host_pools[spec.name])
        return None

    def _decode_shipped_compute(self, sg: _StagedGroup, shipped: list):
        """Dispatch the fused decode+compute executable over shipped
        buffers and shape the :class:`~.compute.PushdownResult`
        (docs/pushdown.md).  Compact mode fetches the (tiny) selected
        count; a count past the static capacity re-dispatches ONCE with
        a grown capacity (``engine.pushdown_overflows``) — a wrong
        (clipped) result can never escape."""
        from . import compute as _compute

        built = sg.compute
        first, slab_dev = shipped[0], shipped[1]
        parts = first if isinstance(first, tuple) else (first,)
        sdict_dev = self._sdict_dev_for(sg.device)
        extra_args = []
        for key in sg.extra_keys:
            rows_d, lens_d = sdict_dev[key]
            extra_args.append(rows_d)
            extra_args.append(lens_d)
        nm = len(built.masks)
        mask_devs = list(shipped[len(shipped) - nm:]) if nm else []
        args = [*parts, slab_dev, *extra_args, *mask_devs]

        def dispatch(cplan):
            with trace.span("decode", attrs={"file": sg.source,
                                             "row_group": sg.group_index,
                                             "rows": sg.num_rows},
                            observe="engine.launch_seconds"):
                return _run_fused(
                    sg.program, len(parts), args, False,
                    device=(
                        sg.device if sg.device is not None else self.device
                    ),
                    cplan=cplan,
                )

        cp = built.cplan
        outs = dispatch(cp)
        trace.count("engine.pushdown_groups")
        trace.count("engine.pushdown_rows_in", int(cp.n))
        if cp.mode == "agg":
            count_dev, agg_outs = outs
            fetched = [np.asarray(a) for a in agg_outs]
            partial = _compute.partial_from_device(built, fetched)
            count = int(count_dev)
            trace.count("engine.pushdown_rows_selected", count)
            return _compute.PushdownResult({}, cp.n, count, agg=partial)
        desc_by = {s.name: d for s, d in zip(sg.program, sg.descs)}
        spec_by = {s.name: s for s in sg.program}

        def expr_dict(ex_outs, trim):
            return {
                name: (
                    vals if trim is None else vals[:trim],
                    mask if mask is None or trim is None
                    else mask[:trim],
                )
                for (name, _et), (vals, mask)
                in zip(cp.exprs, ex_outs)
            }

        if cp.mode == "mask":
            if cp.exprs:
                count_dev, sel, col_outs, ex_outs = outs
            else:
                count_dev, sel, col_outs = outs
                ex_outs = None
            count = int(count_dev)
            built.request.observe(count)
            trace.count("engine.pushdown_rows_selected", count)
            cols = self._compute_columns(
                cp.ship, col_outs, desc_by, spec_by, sg, trim=None
            )
            return _compute.PushdownResult(
                cols, cp.n, count, mask=sel,
                exprs=None if ex_outs is None
                else expr_dict(ex_outs, None),
            )
        count = int(outs[0])
        if count > cp.capacity:
            trace.count("engine.pushdown_overflows")
            built.request.observe(count)
            built.cplan = cp = cp._replace(
                capacity=max(1, min(cp.n, _bucket15(count)))
            )
            outs = dispatch(cp)
            count = int(outs[0])
        built.request.observe(count)
        trace.count("engine.pushdown_rows_selected", count)
        cols = self._compute_columns(
            cp.ship, outs[1], desc_by, spec_by, sg, trim=count
        )
        return _compute.PushdownResult(
            cols, cp.n, count,
            exprs=expr_dict(outs[2], count) if cp.exprs else None,
        )

    def _compute_columns(self, ship, col_outs, desc_by, spec_by, sg,
                         trim):
        """DeviceColumns from a compute launch's column outputs
        (``trim`` slices capacity-padded compact outputs to the true
        selected count)."""
        cols: Dict[str, DeviceColumn] = {}
        for name, (vals, mask, lens) in zip(ship, col_outs):
            if trim is not None:
                vals = vals[:trim]
                mask = None if mask is None else mask[:trim]
                lens = None if lens is None else lens[:trim]
            dc = DeviceColumn(desc_by[name], vals, mask, lens)
            dc.dict_ref = self._dict_ref_for(spec_by[name], sg)
            cols[name] = dc
        return cols

    def read_row_group_compute(self, index: int, request,
                               columns: Optional[Sequence[str]] = None,
                               covered=None):
        """Decode one row group WITH the pushdown compute tail — filter
        (compacted or masked) or partial aggregates — in one fused
        launch (docs/pushdown.md).  ``request`` is a
        :class:`~parquet_floor_tpu.tpu.compute.ComputeRequest`;
        ``columns`` restricts what ships (predicate/aggregate columns
        are staged regardless); ``covered`` optionally narrows the
        decode to page-aligned row ranges (the page-prune rung —
        filtering the cover equals filtering the group, since the cover
        is a superset of every matching row).  Over-cap groups decode
        via the multi-launch chunked path and evaluate the request as
        follow-up device ops — same results, counted by the usual
        chunked-fallback accounting."""
        from . import compute as _compute
        from ..errors import UnsupportedFeatureError

        if self._salvage:
            raise UnsupportedFeatureError(
                "pushdown compute does not run under salvage (quarantine "
                "decisions are group-wide; scan with salvage and filter "
                "on host)"
            )
        rg = self.reader.row_groups[index]
        need = request.columns_needed()
        want = (
            None if columns is None
            else sorted(set(columns) | {c.split(".")[0] for c in need})
        )
        ship = set(columns) if columns is not None else None
        n = int(rg.num_rows or 0)
        est = self._group_byte_estimate(rg, set(want) if want else None)
        if covered is not None:
            cov_rows = sum(b - a for a, b in covered)
            if cov_rows == 0:
                return _compute.PushdownResult(
                    {}, 0, 0,
                    agg=(None if request.aggregate is None
                         else _compute.AggPartial(request.aggregate)),
                )
            if cov_rows * (est / max(n, 1)) > self._arena_cap:
                cols, _cov = self.read_row_group_ranges(
                    index, covered, want
                )
                return self._compute_fallback(cols, request, ship)
            sg = self._stage_row_group(
                index, want, covered=covered, group_rows=n,
                compute=(request, ship),
            )
            return self._decode_shipped_compute(sg, self._ship(sg))
        if est > self._arena_cap:
            cols = self._read_row_group_chunked(rg, index,
                                                set(want) if want else None)
            return self._compute_fallback(cols, request, ship)
        sg = self._stage_row_group(index, want, compute=(request, ship))
        return self._decode_shipped_compute(sg, self._ship(sg))

    def _compute_fallback(self, cols, request, ship):
        """Evaluate a request over already-decoded columns (multi-launch
        groups) and restrict the shipped projection."""
        from . import compute as _compute

        n = (
            int(next(iter(cols.values())).values.shape[0]) if cols else 0
        )
        res = _compute.eval_on_columns(cols, request, n)
        trace.count("engine.pushdown_groups")
        trace.count("engine.pushdown_rows_in", n)
        trace.count("engine.pushdown_rows_selected", res.num_selected)
        if ship is not None:
            res.columns = {
                k: v for k, v in res.columns.items()
                if k in ship or k.split(".")[0] in ship
            }
        return res

    def _launch(self, sg: _StagedGroup, out_perm=None
                ) -> Dict[str, DeviceColumn]:
        return self._decode_shipped(sg, self._ship(sg), out_perm=out_perm)


# ---------------------------------------------------------------------------
# Cross-file pipelining (the scan scheduler's device leg)
# ---------------------------------------------------------------------------

def iter_dataset_row_groups(tasks, columns: Optional[Sequence[str]] = None,
                            prefetch: bool = True,
                            depth_hint: Optional[int] = None):
    """Decode ``(reader, group_index)`` pairs in order, with the 3-stage
    stage‖ship‖decode pipeline running ACROSS reader (file) boundaries.

    ``TpuRowGroupReader.iter_row_groups`` pipelines within one file; this
    is its dataset form: while the device decodes the last group of file
    k, the stage worker is already staging group 0 of file k+1 — the
    pipeline never drains at a file boundary.  All readers must target
    the same device; each keeps its own shape buckets and dictionary
    pools, and files with identical decode shapes share compiled
    programs through the fused-decode jit cache (it is keyed by the
    program tuple, not the reader).

    Oversized groups (footer estimate past their reader's arena cap)
    decode via the multi-launch chunk path outside the pipeline, exactly
    as in the single-file iterator; the runs of normal groups between
    them keep the pipeline.

    ``tasks`` may also be an ITERATOR (anything that is not a
    list/tuple) — the windowed form shuffled training epochs over
    fd-limit-sized datasets need.  Iterator items are ``(reader,
    group_index)`` optionally extended positionally with
    ``close_after``, ``out_perm``, ``compute`` (a
    :class:`~.compute.ComputeRequest` — the group decodes WITH the
    pushdown tail and yields a ``PushdownResult``, docs/pushdown.md)
    and ``covered`` (a page-aligned row cover — the group stages only
    those rows, the device page-prune rung), where ``reader`` may be a
    zero-argument callable returning a ``TpuRowGroupReader`` (a lazy
    open: the file's footer is not touched until the pipeline pulls the
    task, DEPTH ahead of consumption) and ``close_after=True`` marks the
    reader's LAST scheduled group — the reader closes as soon as that
    group is consumed, so at most the in-flight window's worth of files
    is ever open.  ``close_after`` must only be set on a reader's final
    task (the pipeline runs DEPTH ahead; a later task on a closed reader
    is a caller bug).  ``out_perm`` (int32, one entry per row) fuses an
    output row permutation into that group's decode executable — see
    :meth:`TpuRowGroupReader.read_row_group`.  Readers the pipeline
    opened via callables are pipeline-owned: any still open when the
    generator finishes, errors, or is abandoned are closed.  Delivery
    order and decoded bytes are identical to the eager (list) path over
    the same task sequence.

    ``depth_hint`` (iterator form only) retunes the pipeline's DEFAULT
    depth — the latency-adaptive scan scheduler passes the depth the
    measured store RTT justifies (``ScanOptions.adaptive_prefetch``,
    docs/remote.md).  An explicit ``PFTPU_PREFETCH_DEPTH`` env override
    still wins; depth never affects delivery order or bytes, only how
    far ahead the stage worker runs.
    """
    if isinstance(tasks, (list, tuple)):
        tasks = list(tasks)
        if not prefetch or len(tasks) <= 1:
            for r, i in tasks:
                yield r.read_row_group(i, columns)
            return
        # an eager list knows its reader set up front: single-file runs
        # default one level shallower (each level of depth pins a host
        # arena, and there is no file boundary whose footer-warm stage
        # needs the extra hiding room)
        multi_file = len({id(r) for r, _ in tasks}) > 1
        yield from _iter_pipeline_stream(
            iter(tasks), columns, prefetch,
            default_depth="3" if multi_file else "2",
        )
        return
    yield from _iter_pipeline_stream(
        iter(tasks), columns, prefetch,
        default_depth="3" if depth_hint is None else str(int(depth_hint)),
    )


def _iter_pipeline_stream(task_iter, columns, prefetch: bool,
                          default_depth: str = "3"):
    """The stage‖ship‖decode dataset pipeline, driven by a task
    iterator — BOTH faces of ``iter_dataset_row_groups`` run through
    here (the eager list form wraps itself in ``iter``), so there is
    exactly one copy of the submission loop, the drain-then-chunk
    big-group handling, and the tracer-scope threading.

    Two dedicated pools make a true 3-stage pipeline: the stage pool
    runs up to DEPTH tasks ahead (bounded: each staged group pins a
    host arena), the ship worker transfers each group as soon as it is
    staged AND the previous transfer is done (one in flight —
    sync_transfers semantics; readers of one dataset share the single
    ship worker, so transfers never interleave even across files), and
    the consumer's thread dispatches the fused decode while it
    materializes.  Steady-state throughput → max(stage, ship,
    decode+consume) instead of their sum.  ``PFTPU_PREFETCH_DEPTH=1``
    restores single-group lookahead if memory is tight.

    ``PFTPU_STAGE_WORKERS=k`` (default 1) sizes the STAGE pool: on
    multi-file scans, k workers stage k different groups' pages
    concurrently (read + decompress + plan are CPU/IO work that
    parallelizes; the engine's shared state — shape-bucket HWMs,
    dictionary pools, the sticky forced set — is lock-protected or
    GIL-atomic, audited for exactly this).  The in-order admission
    argument is unchanged: ship tasks enqueue on the single ship worker
    in submission order and each blocks on ITS stage future, so
    transfers and deliveries stay in task order no matter which stage
    worker finishes first.  Note the shape buckets grow in STAGING
    order, which k>1 makes nondeterministic — padded widths may differ
    run to run (decoded values never do); leave k=1 where padding
    byte-stability across runs matters.  ``engine.stage_queue_depth_max``
    gauges how deep the submitted-but-undelivered queue actually got.

    With a device MESH active (``parallel.mesh.mesh_devices()`` — on
    by default on a multi-device accelerator backend, opt-in via
    ``PFTPU_MESH_DEVICES`` elsewhere), staged groups round-robin across
    the k local devices: each device gets its OWN single-worker ship
    pool (H2D transfers overlap across chips, stay serialized per
    chip), its own dictionary pool, and its own persistent exec-cache
    entry (the cache key carries ``platform:id``), and the fused decode
    dispatches ON the device's worker.  Delivery order is still strict
    submission order — the queue pops in the order groups were
    submitted and each entry's future completes on its own device — so
    every read face inherits the fan-out bit-identically (padded
    string widths follow the ``PFTPU_STAGE_WORKERS>1`` contract;
    docs/multichip.md).  The stage pool defaults to k workers and the
    prefetch depth to 2k so every chip has work; big groups and
    salvage units keep the single-device path.

    Because tasks pull lazily, files open DEPTH-ahead of consumption
    and close right after their last scheduled group (``close_after``)
    — the fd-bounded form ``iter_dataset_row_groups`` documents."""
    import os as _os

    from ..parallel import mesh as _mesh

    want = set(columns) if columns else None
    mesh_devs = _mesh.mesh_devices() if prefetch else []
    mesh_on = len(mesh_devs) > 1
    DEPTH = max(1, int(
        _os.environ.get("PFTPU_PREFETCH_DEPTH", default_depth)
    ))
    if mesh_on and "PFTPU_PREFETCH_DEPTH" not in _os.environ:
        # keep every chip fed: k groups decoding + k staging ahead
        DEPTH = max(DEPTH, 2 * len(mesh_devs))
    # stage/ship tasks bind to the caller's tracer scope: concurrent
    # scans under separate trace.scope()s keep their stage‖ship spans
    # attributed even though each scan spawns its own worker threads
    tracer = trace.current()
    owned: List[TpuRowGroupReader] = []   # opened via task callables
    closed: List[TpuRowGroupReader] = []  # already closed (identity)

    def norm(item):
        """Resolve one task item to (reader, group_index, close_after,
        out_perm, compute, covered), opening lazy readers (and recording
        ownership) on the way.  ``compute`` is a
        :class:`~.compute.ComputeRequest` (pushdown — docs/pushdown.md);
        ``covered`` a page-aligned row cover (the device scan leg's
        page-prune rung)."""
        r, gi = item[0], item[1]
        ca = bool(item[2]) if len(item) > 2 else False
        perm = item[3] if len(item) > 3 else None
        comp = item[4] if len(item) > 4 else None
        cov = item[5] if len(item) > 5 else None
        if callable(r) and not isinstance(r, TpuRowGroupReader):
            r = r()
            if not any(o is r for o in owned):
                owned.append(r)
        return r, int(gi), ca, perm, comp, cov

    def retire(r):
        """Close a reader whose last scheduled group was just consumed."""
        if any(c is r for c in closed):
            return
        closed.append(r)
        r.close()

    def read_direct(r, gi, perm, comp, cov):
        """One unpipelined read honoring every task flavor (the
        no-prefetch path and the drain-then-chunk big-group path)."""
        if comp is not None:
            return r.read_row_group_compute(
                gi, comp, columns=columns, covered=cov
            )
        if cov is not None:
            cols, _covered = r.read_row_group_ranges(gi, cov, columns)
            if perm is not None:
                cols = _permuted_columns(cols, perm)
            return cols
        return r.read_row_group(gi, columns, out_perm=perm)

    try:
        if not prefetch:
            for item in task_iter:
                r, gi, ca, perm, comp, cov = norm(item)
                yield read_direct(r, gi, perm, comp, cov)
                if ca:
                    retire(r)
            return

        def ship_task(r, stage_fut):
            sg = stage_fut.result()
            return r, sg, r._ship(sg)

        def mesh_ship_task(r, stage_fut, perm):
            # runs on the group's DEVICE worker: ship + decode dispatch
            # both happen chip-locally, so k chips transfer and warm
            # their exec-cache entries concurrently; the consumer only
            # collects the (already in-flight) result, in order
            sg = stage_fut.result()
            shipped = r._ship(sg)
            return r._decode_shipped(sg, shipped, out_perm=perm)

        stage_workers = min(DEPTH, max(1, int(
            _os.environ.get(
                "PFTPU_STAGE_WORKERS",
                str(len(mesh_devs)) if mesh_on else "1",
            )
        )))
        # salvage decodes mutate per-reader report state and must fold
        # deterministically — they serialize through this lock even
        # when the stage pool runs several workers
        salv_lock = threading.Lock()

        def salv_task(r, gi, perm, cov):
            with salv_lock:
                out = r._read_row_group_salvage(
                    gi, columns, perm, row_ranges=cov
                )
                return out[0] if cov is not None else out

        if mesh_on:
            trace.decision("engine.mesh", {
                "devices": len(mesh_devs),
                "platform": getattr(mesh_devs[0], "platform", "?"),
            })
            trace.gauge_max("engine.mesh_devices", len(mesh_devs))
        rr = 0  # round-robin cursor over mesh_devs

        with ThreadPoolExecutor(max_workers=stage_workers,
                                thread_name_prefix="pftpu-stage") as sp, \
                ThreadPoolExecutor(max_workers=1,
                                   thread_name_prefix="pftpu-ship") as shp, \
                _mesh.DevicePools(mesh_devs if mesh_on else []) as dpools:
            # entries: ("pipe", reader, close_after, perm, ship_future),
            # ("pipem", reader, close_after, decode_future) — the mesh
            # placement: ship AND decode ride the group's device worker,
            # ("big", reader, group_index, close_after, perm), or
            # ("salv", reader, close_after, future) — salvage readers
            # host-decode each group on the stage worker (one-deep
            # overlap preserved; there is nothing to ship separately,
            # the salvage path device_puts its surviving arrays itself)
            q: deque = deque()
            blocked = False  # a big group is queued: stop submitting

            def submit_one():
                nonlocal blocked, rr
                if blocked:
                    return False
                item = next(task_iter, None)
                if item is None:
                    return False
                r, gi, ca, perm, comp, cov = norm(item)
                if getattr(r, "_salvage", False):
                    f = sp.submit(tracer.run, salv_task, r, gi, perm, cov)
                    q.append(("salv", r, ca, f))
                    trace.gauge_max("engine.stage_queue_depth_max", len(q))
                    return True
                rg = r.reader.row_groups[gi]
                est = r._group_byte_estimate(rg, want)
                if cov is not None:
                    # a page-pruned group stages only its covered rows:
                    # scale the footer estimate by the cover fraction
                    n_all = max(int(rg.num_rows or 0), 1)
                    est = int(est * min(
                        sum(b - a for a, b in cov) / n_all, 1.0
                    ))
                big = est > r._arena_cap
                if big:
                    # drain-then-chunk, exactly the eager path's contract:
                    # everything already queued delivers first, nothing
                    # new submits, so when this entry is popped both
                    # workers are idle and the multi-launch chunk path
                    # owns the link
                    q.append(("big", r, gi, ca, perm, comp, cov))
                    blocked = True
                else:
                    # chunked=False: intra-group chunked shipping would
                    # issue transfers from the stage worker concurrently
                    # with the ship worker's — two streams contend on
                    # tunnelled links (single-group reads take
                    # read_row_group's chunked path instead)
                    kwargs = dict(chunked=False)
                    if cov is not None:
                        kwargs.update(
                            covered=cov, group_rows=int(rg.num_rows or 0)
                        )
                    if comp is not None:
                        kwargs.update(compute=(
                            comp, set(columns) if columns else None
                        ))
                    if mesh_on:
                        dev = mesh_devs[rr % len(mesh_devs)]
                        rr += 1
                        kwargs.update(device=dev)
                        f = sp.submit(
                            tracer.run, partial(
                                r._stage_row_group, gi, columns, **kwargs
                            ),
                        )
                        trace.count("engine.mesh_groups")
                        q.append((
                            "pipem", r, ca,
                            dpools.submit(
                                dev, tracer.run, mesh_ship_task, r, f, perm
                            ),
                        ))
                    else:
                        f = sp.submit(
                            tracer.run, partial(
                                r._stage_row_group, gi, columns, **kwargs
                            ),
                        )
                        q.append((
                            "pipe", r, ca, perm,
                            shp.submit(tracer.run, ship_task, r, f),
                        ))
                trace.gauge_max("engine.stage_queue_depth_max", len(q))
                return True

            for _ in range(DEPTH):
                if not submit_one():
                    break
            while q:
                entry = q.popleft()
                if entry[0] == "big":
                    _, r, gi, ca, perm, comp, cov = entry
                    yield read_direct(r, gi, perm, comp, cov)
                    blocked = False
                elif entry[0] == "salv":
                    _, r, ca, fut = entry
                    yield fut.result()
                elif entry[0] == "pipem":
                    _, r, ca, fut = entry
                    yield fut.result()
                else:
                    _, r, ca, perm, fut = entry
                    r2, sg, shipped = fut.result()
                    yield r2._decode_shipped(sg, shipped, out_perm=perm)
                if ca:
                    retire(r)
                while len(q) < DEPTH and submit_one():
                    pass
    finally:
        # pipeline-owned readers left open (error, abandonment, or a
        # task list that never set close_after) close here — AFTER the
        # with-block above joined the stage/ship workers, so no in-flight
        # stage read can race a close
        for r in owned:
            if not any(c is r for c in closed):
                r.close()
