"""Batched TPU row-group decode engine.

Replaces the reference's per-cell pull loop (``ParquetReader.java:176-212``)
with the SURVEY.md §3.2 boundary note made real: the host reads raw column
chunks, normalizes pages (decompress via the native codec, parse run tables
— O(runs), tiny), ships flat byte buffers + plan arrays to HBM once, and a
single jitted function per column expands, gathers, and scatters the whole
row group on device.

Decode paths on device (all static-shaped, jit-cached per
(path, n, bit widths, dtype)):
  * RLE_DICTIONARY fixed-width   — run expand → dictionary take → null scatter
  * RLE_DICTIONARY BYTE_ARRAY    — run expand → padded-matrix take
  * PLAIN fixed-width            — bitcast → null scatter
  * PLAIN BOOLEAN                — per-page bit-packed runs → run expand
  * DELTA_BINARY_PACKED (≤32-bit miniblocks, single page) — delta expand
Anything else falls back to the host NumPy engine and is shipped dense.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..format import codecs
from ..format import pages as pg
from ..format.encodings import rle_hybrid as e_rle
from ..format.encodings.plain import ByteArrayColumn, decode_plain
from ..format.file_read import ParquetFileReader
from ..format.parquet_thrift import (
    CompressionCodec,
    Encoding,
    PageType,
    Type,
)
from ..format.schema import ColumnDescriptor
from . import bitops

def _require_x64() -> None:
    """64-bit decode correctness requires x64 (int64 is exact on TPU via
    emulation; float64 is NOT — see the float64 policy).  Checked at reader
    construction rather than forced at import: flipping global dtype
    semantics as an import side effect would silently change the numerics
    of unrelated user code."""
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "parquet_floor_tpu's TPU engine needs 64-bit JAX types for "
            "INT64/DOUBLE columns: call "
            'jax.config.update("jax_enable_x64", True) before creating a '
            "TpuRowGroupReader"
        )

_JNP_DTYPE = {
    Type.INT32: jnp.int32,
    Type.INT64: jnp.int64,
    Type.FLOAT: jnp.float32,
    Type.DOUBLE: jnp.float64,
}
_NP_DTYPE = {
    Type.INT32: np.int32,
    Type.INT64: np.int64,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
}


def _platform_is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def f64bits_to_f32(bits: jax.Array) -> jax.Array:
    """Convert IEEE-754 double bit patterns (int64) to float32 on device.

    TPU emulates float64 at ~49-bit precision, so a straight f64 bitcast is
    lossy; instead DOUBLE columns decode bit-exactly to int64 and convert to
    the TPU compute dtype with explicit bit math.  Subnormals flush to zero
    (TPU semantics); infinities and NaN are preserved.
    """
    sign = (bits < 0)
    exp = ((bits >> 52) & 0x7FF).astype(jnp.int32)
    mant = (bits & ((1 << 52) - 1))
    # 1.mant as float32: one correctly-rounded int→float conversion, then
    # exact power-of-two scalings — equivalent to rounding the f64 directly.
    # (jnp.exp2 is an approximation on f32; build 2^e exactly from the
    # exponent field instead.)
    frac = (mant | (1 << 52)).astype(jnp.float32) * jnp.float32(2.0**-52)
    e = exp - 1023
    e_clamped = jnp.clip(e, -126, 127)
    pow2 = jax.lax.bitcast_convert_type(
        ((e_clamped + 127) << 23).astype(jnp.int32), jnp.float32
    )
    magnitude = frac * pow2
    magnitude = jnp.where(e > 127, jnp.float32(jnp.inf), magnitude)
    magnitude = jnp.where(e < -126, jnp.float32(0.0), magnitude)  # flush tiny
    magnitude = jnp.where(exp == 0, jnp.float32(0.0), magnitude)
    is_special = exp == 0x7FF
    special = jnp.where(
        mant == 0, jnp.float32(jnp.inf), jnp.float32(jnp.nan)
    )
    magnitude = jnp.where(is_special, special, magnitude)
    return jnp.where(sign, -magnitude, magnitude)


@dataclass
class DeviceColumn:
    """One decoded column living on device."""

    descriptor: ColumnDescriptor
    values: jax.Array               # dense (num_rows, ...) values; nulls filled
    mask: Optional[jax.Array]       # True where null; None if required
    lengths: Optional[jax.Array] = None  # for strings: per-row byte lengths

    @property
    def is_strings(self) -> bool:
        return self.lengths is not None

    def to_numpy_dense(self):
        return np.asarray(self.values), (None if self.mask is None else np.asarray(self.mask))


# ---------------------------------------------------------------------------
# Host-side page normalization
# ---------------------------------------------------------------------------

@dataclass
class _NormPages:
    """Uncompressed, concatenated page streams for one chunk."""

    levels_buf: np.ndarray          # concat of def-level streams (unframed)
    values_buf: np.ndarray          # concat of value streams
    # per page: (n_values, n_non_null, level_byte_base, value_byte_base,
    #            value_encoding)
    page_n: List[int]
    page_nn: List[int]
    page_level_base: List[int]
    page_value_base: List[int]
    page_encoding: List[int]
    def_bw: int
    max_def: int
    # level run tables parsed during normalization (V1 pages parse them for
    # the non-null count anyway); byte offsets are relative to the page's
    # level stream.  None → parse lazily in _merged_level_plan (V2 pages).
    page_level_table: List[Optional[np.ndarray]] = None


def _normalize_pages(
    raw_pages: List[pg.RawPage], desc: ColumnDescriptor, codec: int
) -> Tuple[Optional[np.ndarray], _NormPages]:
    """Decompress + split every data page into (levels, values) streams.

    Returns (dictionary_plain_bytes_or_None, _NormPages).  Rep levels are
    rejected here (nested columns use the host Dremel path).
    """
    if desc.max_repetition_level > 0:
        raise _Fallback("repeated column")
    max_def = desc.max_definition_level
    def_bw = e_rle.min_bit_width(max_def)
    levels_parts: List[bytes] = []
    values_parts: List[bytes] = []
    meta = _NormPages(
        levels_buf=np.zeros(0, np.uint8),
        values_buf=np.zeros(0, np.uint8),
        page_n=[], page_nn=[], page_level_base=[], page_value_base=[],
        page_encoding=[], def_bw=def_bw, max_def=max_def,
        page_level_table=[],
    )
    dict_bytes: Optional[np.ndarray] = None
    lvl_pos = 0
    val_pos = 0
    for page in raw_pages:
        if page.page_type == PageType.DICTIONARY_PAGE:
            dh = page.header.dictionary_page_header
            if dh.encoding not in (Encoding.PLAIN, Encoding.PLAIN_DICTIONARY):
                raise _Fallback("non-PLAIN dictionary page")
            data = codecs.decompress(codec, page.payload, page.header.uncompressed_page_size)
            dict_bytes = np.frombuffer(data, dtype=np.uint8)
            continue
        if page.page_type == PageType.DATA_PAGE:
            h = page.header.data_page_header
            data = codecs.decompress(codec, page.payload, page.header.uncompressed_page_size)
            pos = 0
            n = h.num_values
            lvl_table = None
            if max_def > 0:
                if h.definition_level_encoding not in (Encoding.RLE, None):
                    raise _Fallback("non-RLE def levels")
                ln = int.from_bytes(data[pos : pos + 4], "little")
                levels_parts.append(data[pos + 4 : pos + 4 + ln])
                level_base, lvl_pos = lvl_pos, lvl_pos + ln
                pos += 4 + ln
                # count non-nulls cheaply from the run table
                table, _ = e_rle.parse_runs(data, n, def_bw, pos - ln)
                nn = _count_non_null(data, table, n, def_bw, max_def)
                # rebase bit-packed offsets to the level stream start so the
                # merged plan can reuse this parse
                lvl_table = table.copy()
                lvl_table[lvl_table[:, 0] == 1, 2] -= pos - ln
            else:
                level_base = 0
                nn = n
            values_parts.append(data[pos:])
            value_base, val_pos = val_pos, val_pos + len(data) - pos
            enc = h.encoding
            meta.page_level_table.append(lvl_table)
        elif page.page_type == PageType.DATA_PAGE_V2:
            h2 = page.header.data_page_header_v2
            n = h2.num_values
            rl = h2.repetition_levels_byte_length or 0
            dl = h2.definition_levels_byte_length or 0
            payload = page.payload
            if rl:
                raise _Fallback("repetition levels present")
            if max_def > 0:
                levels_parts.append(bytes(payload[rl : rl + dl]))
                level_base, lvl_pos = lvl_pos, lvl_pos + dl
            else:
                level_base = 0
            body = payload[rl + dl :]
            compressed = h2.is_compressed if h2.is_compressed is not None else True
            if compressed and codec != CompressionCodec.UNCOMPRESSED:
                expected = page.header.uncompressed_page_size - rl - dl
                body = codecs.decompress(codec, body, expected)
            nn = n - (h2.num_nulls or 0)
            values_parts.append(bytes(body))
            value_base, val_pos = val_pos, val_pos + len(body)
            enc = h2.encoding
            meta.page_level_table.append(None)
        elif page.page_type == PageType.INDEX_PAGE:
            continue
        else:
            raise _Fallback(f"page type {page.page_type}")
        meta.page_n.append(n)
        meta.page_nn.append(nn)
        meta.page_level_base.append(level_base)
        meta.page_value_base.append(value_base)
        meta.page_encoding.append(enc)
    meta.levels_buf = _concat_padded(levels_parts)
    meta.values_buf = _concat_padded(values_parts)
    return dict_bytes, meta


def _concat_padded(parts: List[bytes]) -> np.ndarray:
    total = sum(len(p) for p in parts)
    out = np.empty(total + 8, dtype=np.uint8)  # +8: extract_bits window pad
    out[total:] = 0
    pos = 0
    for p in parts:
        out[pos : pos + len(p)] = np.frombuffer(p, dtype=np.uint8)
        pos += len(p)
    return out


def _count_non_null(data, table, n, def_bw, max_def) -> int:
    """Non-null count from the run table alone (no full expansion: RLE runs
    compare one value; only bit-packed runs unpack — levels are usually
    RLE-dominated)."""
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    nn = 0
    for kind, count, v, _ in table:
        if kind == 0:
            if v == max_def:
                nn += int(count)
        else:
            nbytes = ((int(count) + 7) // 8) * def_bw
            vals = e_rle.bit_unpack(buf[v : v + nbytes], def_bw, int(count))
            nn += int(np.count_nonzero(vals == max_def))
    return nn


class _Fallback(Exception):
    """Signal: this chunk takes the host NumPy path."""


@dataclass
class _Staged:
    """Host-staged chunk: arrays awaiting transfer + the launch closure that
    turns their device copies into a DeviceColumn (runs on the main thread)."""

    arrays: list
    launch: object  # Callable[[list], DeviceColumn]


def _padded_rows(col: ByteArrayColumn):
    """Vectorized (n, max_len) uint8 matrix + lengths from a ByteArrayColumn
    (the device-friendly string layout)."""
    lengths = col.lengths().astype(np.int32)
    n = len(col)
    max_len = max(int(lengths.max()) if n else 1, 1)
    if n == 0:
        return np.zeros((0, max_len), np.uint8), lengths, max_len
    data = col.data
    if len(data) == 0:
        return np.zeros((n, max_len), np.uint8), lengths, max_len
    idx = col.offsets[:-1, None] + np.arange(max_len)[None, :]
    valid = np.arange(max_len)[None, :] < lengths[:, None]
    rows = np.where(valid, data[np.minimum(idx, len(data) - 1)], np.uint8(0))
    return rows.astype(np.uint8), lengths, max_len


# ---------------------------------------------------------------------------
# Plan building (host): run tables across pages → device arrays
# ---------------------------------------------------------------------------

def _merged_level_plan(meta: _NormPages):
    """Concatenate per-page def-level run tables into one device plan.

    Output offsets fall out of the concatenation itself (each page's table
    covers exactly its value count, and ``run_table_to_device_plan`` cumsums
    the counts); only bit-packed byte offsets need rebasing to the
    concatenated buffer."""
    tables = []
    for i, n in enumerate(meta.page_n):
        cached = (
            meta.page_level_table[i]
            if meta.page_level_table and i < len(meta.page_level_table)
            else None
        )
        if cached is not None:
            table = cached
        else:
            ln_end = (
                meta.page_level_base[i + 1]
                if i + 1 < len(meta.page_n)
                else len(meta.levels_buf) - 8
            )
            page_stream = meta.levels_buf[meta.page_level_base[i] : ln_end]
            table, _ = e_rle.parse_runs(page_stream, n, meta.def_bw)
        if len(table):
            t = table.copy()
            bp = t[:, 0] == 1
            t[bp, 2] += meta.page_level_base[i]  # absolute byte offset
            tables.append(t)
    total_n = sum(meta.page_n)
    merged = np.concatenate(tables) if tables else np.zeros((0, 4), np.int64)
    pad = bitops.bucket_size(max(len(merged), 1), 16)
    plan = bitops.run_table_to_device_plan(merged, total_n, pad)
    return plan, total_n


def _merged_index_plan(meta: _NormPages):
    """Concatenate per-page dictionary-index run tables; returns plan + bw."""
    tables = []
    bw = None
    total_nn = sum(meta.page_nn)
    for i, nn in enumerate(meta.page_nn):
        base = meta.page_value_base[i]
        page_bw = int(meta.values_buf[base])
        if bw is None:
            bw = page_bw
        elif page_bw != bw:
            raise _Fallback("mixed index bit widths across pages")
        if bw == 0:
            tables.append(np.zeros((0, 4), np.int64))
            continue
        end = (
            meta.page_value_base[i + 1]
            if i + 1 < len(meta.page_n)
            else len(meta.values_buf) - 8
        )
        stream = meta.values_buf[base + 1 : end]
        table, _ = e_rle.parse_runs(stream, nn, bw)
        t = table.copy()
        bp = t[:, 0] == 1
        t[bp, 2] += base + 1
        tables.append(t)
    merged = np.concatenate(tables) if tables else np.zeros((0, 4), np.int64)
    pad = bitops.bucket_size(max(len(merged), 1), 16)
    plan = bitops.run_table_to_device_plan(merged, total_nn, pad)
    return plan, (bw or 1), total_nn


def parse_delta_plan(data_u8: np.ndarray, dtype) -> Optional[dict]:
    """Host parse of a DELTA_BINARY_PACKED stream into a device miniblock
    plan.  Returns None (→ host fallback) when the stream needs >32-bit
    arithmetic — including when any reachable *prefix sum* can leave int32
    range, tracked by interval arithmetic over the miniblock bounds (for
    int32 output, wraparound is the spec semantics, so no range check)."""
    data = bytes(data_u8)
    pos = 0
    block_size, pos = e_rle._read_varint(data, pos)
    n_mini, pos = e_rle._read_varint(data, pos)
    total, pos = e_rle._read_varint(data, pos)
    first, pos = _read_zigzag(data, pos)
    if n_mini == 0 or block_size % n_mini:
        return None
    per_mini = block_size // n_mini
    check_range = np.dtype(dtype).itemsize > 4
    i32 = (-(2**31), 2**31 - 1)
    if not (-(2**31) <= first < 2**31):
        return None
    lo = hi = first  # reachable value interval across all prefix sums
    mb_bitbase, mb_bw, mb_min = [], [], []
    got = 0
    n_deltas = total - 1
    while got < n_deltas:
        min_delta, pos = _read_zigzag(data, pos)
        if not (-(2**31) <= min_delta < 2**31):
            return None
        widths = data[pos : pos + n_mini]
        pos += n_mini
        for m in range(n_mini):
            if got >= n_deltas:
                break
            bwm = widths[m]
            if bwm > 32:
                return None
            count = min(per_mini, n_deltas - got)
            if check_range:
                # Every delta in this miniblock lies in [d_lo, d_hi]; the
                # lowest reachable prefix adds count*d_lo when d_lo < 0
                # (monotone dip), else never dips below the entry value —
                # symmetrically for the high side.
                d_lo = min_delta
                d_hi = min_delta + ((1 << bwm) - 1)
                lo += count * d_lo if d_lo < 0 else 0
                hi += count * d_hi if d_hi > 0 else 0
                if lo < i32[0] or hi > i32[1]:
                    return None
            mb_bitbase.append(pos * 8)
            mb_bw.append(bwm)
            mb_min.append(min_delta)
            got += count
            pos += per_mini * bwm // 8
    m = max(len(mb_bw), 1)
    pad = bitops.bucket_size(m, 4)
    return {
        "mb_bitbase": bitops.pad_to(np.array(mb_bitbase or [0], np.int32), pad),
        "mb_bw": bitops.pad_to(np.array(mb_bw or [0], np.int32), pad),
        "mb_min_delta": bitops.pad_to(np.array(mb_min or [0], np.int32), pad),
        "first_value": int(first),
        "values_per_miniblock": per_mini,
        "total": total,
        "end_pos": pos,
    }


def _read_zigzag(data, pos):
    v, pos = e_rle._read_varint(data, pos)
    return (v >> 1) ^ -(v & 1), pos


# ---------------------------------------------------------------------------
# Jitted device decode functions (static args define the jit cache key)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n", "bw"))
def _expand_runs_dev(buf, out_end, kind, value, bitbase, *, n, bw):
    return bitops.rle_expand(buf, out_end, kind, value, bitbase, n, bw)


@partial(jax.jit, static_argnames=("n", "bw", "max_def", "def_bw", "nn"))
def _dict_decode_opt(
    vbuf, lbuf, dictionary,
    i_end, i_kind, i_val, i_base,
    d_end, d_kind, d_val, d_base,
    *, n, bw, max_def, def_bw, nn,
):
    levels = bitops.rle_expand(lbuf, d_end, d_kind, d_val, d_base, n, def_bw)
    present = levels == max_def
    idx = bitops.rle_expand(vbuf, i_end, i_kind, i_val, i_base, nn, bw)
    vals = bitops.dict_gather(dictionary, idx)
    dense = bitops.dense_scatter(vals, present)
    return dense, ~present


@partial(jax.jit, static_argnames=("n", "bw"))
def _dict_decode_req(vbuf, dictionary, i_end, i_kind, i_val, i_base, *, n, bw):
    idx = bitops.rle_expand(vbuf, i_end, i_kind, i_val, i_base, n, bw)
    return bitops.dict_gather(dictionary, idx)


def _bitcast_values(vbuf, n, dtype, f64_as_f32):
    if f64_as_f32 and dtype == jnp.float64:
        bits = bitops.bitcast_bytes(vbuf, jnp.int64, n)  # exact on TPU
        return f64bits_to_f32(bits)
    return bitops.bitcast_bytes(vbuf, dtype, n)


@partial(jax.jit, static_argnames=("n", "dtype", "f64_as_f32"))
def _plain_decode_req(vbuf, *, n, dtype, f64_as_f32=False):
    return _bitcast_values(vbuf, n, dtype, f64_as_f32)


@partial(jax.jit, static_argnames=("n", "nn", "dtype", "max_def", "def_bw", "f64_as_f32"))
def _plain_decode_opt(
    vbuf, lbuf, d_end, d_kind, d_val, d_base,
    *, n, nn, dtype, max_def, def_bw, f64_as_f32=False,
):
    levels = bitops.rle_expand(lbuf, d_end, d_kind, d_val, d_base, n, def_bw)
    present = levels == max_def
    vals = _bitcast_values(vbuf, nn, dtype, f64_as_f32)
    return bitops.dense_scatter(vals, present), ~present


@partial(jax.jit, static_argnames=("n", "max_len"))
def _dict_strings_opt_gather(dict_rows, dict_lens, idx, present, *, n, max_len):
    rows = jnp.take(dict_rows, idx, axis=0)
    lens = jnp.take(dict_lens, idx)
    dense_rows = bitops.dense_scatter(rows, present)
    dense_lens = bitops.dense_scatter(lens, present)
    return dense_rows, dense_lens


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------

class TpuRowGroupReader:
    """Decode row groups of a parquet file into device-resident columns.

    The batch-columnar sibling of the row-streaming API: same file, same
    footer, but each column becomes one ``jax.Array`` per row group.
    """

    def __init__(self, source, device: Optional[jax.Device] = None,
                 float64_policy: str = "auto", host_threads: Optional[int] = None):
        """``float64_policy``: how DOUBLE columns materialize on device —
        "auto" (exact float64 on CPU; float32 on TPU, where f64 is emulated
        and lossy anyway), "float64", "float32", or "bits" (exact int64 bit
        patterns).

        ``host_threads``: size of the host staging pool that decodes column
        chunks concurrently (native decompress + run-table parse release the
        GIL).  0/1 disables; None picks a default from the CPU count."""
        _require_x64()
        self.reader = source if isinstance(source, ParquetFileReader) else ParquetFileReader(source)
        self.device = device
        if float64_policy not in ("auto", "float64", "float32", "bits"):
            raise ValueError(f"bad float64_policy {float64_policy!r}")
        if float64_policy == "auto":
            float64_policy = "float32" if _platform_is_tpu() else "float64"
        self.float64_policy = float64_policy
        self._string_dict_cache: Dict[bytes, tuple] = {}   # host padded pools
        self._string_dict_dev: Dict[bytes, tuple] = {}     # device copies (main thread)
        if host_threads is None:
            host_threads = min(8, os.cpu_count() or 1)
        self._pool = (
            ThreadPoolExecutor(max_workers=host_threads, thread_name_prefix="pftpu-stage")
            if host_threads and host_threads > 1
            else None
        )
        self._dict_lock = threading.Lock()

    @property
    def metadata(self):
        return self.reader.metadata

    @property
    def num_row_groups(self) -> int:
        return len(self.reader.row_groups)

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        self.reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- public -------------------------------------------------------------

    def read_row_group(
        self, index: int, columns: Optional[Sequence[str]] = None
    ) -> Dict[str, DeviceColumn]:
        rg = self.reader.row_groups[index]
        want = set(columns) if columns else None
        work = []
        for chunk in rg.columns or []:
            name = chunk.meta_data.path_in_schema[0]
            if want and name not in want:
                continue
            desc = self.reader.schema.column(tuple(chunk.meta_data.path_in_schema))
            work.append((name, chunk, desc))
        # Phase 1 — host staging (parallel): decompress, parse run tables,
        # build device plans.  Native codec + RLE parse release the GIL.
        if self._pool is not None and len(work) > 1:
            staged = list(self._pool.map(lambda w: self._stage_chunk(w[1], w[2]), work))
        else:
            staged = [self._stage_chunk(c, d) for _, c, d in work]
        # Phase 2 — one batched host→device transfer for the whole row group.
        dev = jax.device_put([s.arrays for s in staged], self.device)
        # Phase 3 — launch the jitted decode functions from this one thread
        # (JAX dispatch is async; concurrent dispatch just contends on locks).
        out: Dict[str, DeviceColumn] = {}
        for (name, _, _), s, d in zip(work, staged, dev):
            out[name] = s.launch(d)
        return out

    # -- per-chunk ----------------------------------------------------------

    def _stage_chunk(self, chunk, desc: ColumnDescriptor) -> "_Staged":
        meta = chunk.meta_data
        try:
            raw_pages = self.reader.read_raw_column_chunk(chunk)
            dict_bytes, norm = _normalize_pages(raw_pages, desc, meta.codec)
            encs = set(norm.page_encoding)
            if not norm.page_n:
                raise _Fallback("empty chunk")
            if encs <= {Encoding.RLE_DICTIONARY, Encoding.PLAIN_DICTIONARY}:
                if dict_bytes is None:
                    raise _Fallback("dictionary pages missing")
                return self._stage_dict(desc, dict_bytes, norm)
            if encs == {Encoding.PLAIN}:
                return self._stage_plain(desc, norm)
            if encs == {Encoding.DELTA_BINARY_PACKED} and len(norm.page_n) == 1:
                return self._stage_delta(desc, norm)
            raise _Fallback(f"encodings {sorted(encs)}")
        except _Fallback:
            return self._stage_host(chunk, desc)

    def _stage_dict(self, desc, dict_bytes: np.ndarray, norm: _NormPages) -> "_Staged":
        n = sum(norm.page_n)
        idx_plan, bw, nn = _merged_index_plan(norm)
        num_dict = self._dict_num_values(dict_bytes, desc)
        pt = desc.physical_type
        if pt in _NP_DTYPE:
            dictionary = np.frombuffer(
                bytes(dict_bytes), dtype=_NP_DTYPE[pt], count=num_dict
            )
            if pt == Type.DOUBLE:
                # dictionary is tiny: convert on host per policy (correctly
                # rounded), gather stays on device
                if self.float64_policy == "float32":
                    dictionary = dictionary.astype(np.float32)
                elif self.float64_policy == "bits":
                    dictionary = dictionary.view(np.int64)
            return self._stage_fixed_dict(desc, dictionary, idx_plan, bw, norm, n, nn)
        if pt == Type.BYTE_ARRAY:
            return self._stage_string_dict(desc, dict_bytes, idx_plan, bw, norm, n, nn)
        raise _Fallback(f"dict decode for type {Type.name(pt)}")

    def _dict_num_values(self, dict_bytes, desc) -> int:
        # dictionary page num_values is authoritative; recover it from size
        pt = desc.physical_type
        if pt in _NP_DTYPE:
            return len(dict_bytes) // np.dtype(_NP_DTYPE[pt]).itemsize
        return -1  # strings: computed during pool parse

    def _stage_fixed_dict(self, desc, dictionary, idx_plan, bw, norm, n, nn) -> "_Staged":
        max_def = desc.max_definition_level
        def_bw = norm.def_bw
        if max_def > 0:
            lvl_plan, _ = _merged_level_plan(norm)

            def launch(dev):
                vbuf, dict_dev, ip, lbuf, lp = dev
                dense, mask = _dict_decode_opt(
                    vbuf, lbuf, dict_dev,
                    ip["run_out_end"], ip["run_kind"], ip["run_value"], ip["run_bitbase"],
                    lp["run_out_end"], lp["run_kind"], lp["run_value"], lp["run_bitbase"],
                    n=n, bw=bw, max_def=max_def, def_bw=def_bw, nn=nn,
                )
                return DeviceColumn(desc, dense, mask)

            return _Staged(
                [norm.values_buf, dictionary, idx_plan, norm.levels_buf, lvl_plan],
                launch,
            )

        def launch(dev):
            vbuf, dict_dev, ip = dev
            dense = _dict_decode_req(
                vbuf, dict_dev,
                ip["run_out_end"], ip["run_kind"], ip["run_value"], ip["run_bitbase"],
                n=n, bw=bw,
            )
            return DeviceColumn(desc, dense, None)

        return _Staged([norm.values_buf, dictionary, idx_plan], launch)

    def _stage_string_dict(self, desc, dict_bytes, idx_plan, bw, norm, n, nn) -> "_Staged":
        # Parse the PLAIN dictionary pool into a padded row matrix once
        # (keyed by content — dict handles hash collisions by comparison).
        key = dict_bytes.tobytes()
        # Ship the padded pool only if no device copy exists yet.  (Racy read
        # from a staging thread: worst case the pool ships once more and the
        # launch-thread cache ignores it.)
        ship_dict = key not in self._string_dict_dev
        with self._dict_lock:
            cached = self._string_dict_cache.get(key)
        if ship_dict and (cached is None or cached[0] is None):
            col, _ = decode_plain(key, _count_plain_strings(dict_bytes), Type.BYTE_ARRAY)
            rows, lengths, max_len = _padded_rows(col)
            cached = (rows, lengths, max_len)
            with self._dict_lock:
                self._string_dict_cache[key] = cached
        host_rows, host_lens, max_len = cached
        max_def = desc.max_definition_level
        def_bw = norm.def_bw
        lvl_plan = _merged_level_plan(norm)[0] if max_def > 0 else None

        def launch(dev):
            # device-side dictionary cache is touched on the launch thread only
            if ship_dict:
                dcached = self._string_dict_dev.setdefault(key, (dev[0], dev[1]))
                dev = dev[2:]
                with self._dict_lock:
                    # device copy now authoritative: drop the host pool matrix,
                    # keep max_len (still needed by later stages)
                    self._string_dict_cache[key] = (None, None, max_len)
            else:
                dcached = self._string_dict_dev[key]
            dict_rows, dict_lens = dcached
            if max_def > 0:
                vbuf, ip, lbuf, lp = dev
            else:
                vbuf, ip = dev
                lbuf = lp = None
            idx = _expand_runs_dev(
                vbuf, ip["run_out_end"], ip["run_kind"], ip["run_value"], ip["run_bitbase"],
                n=nn, bw=bw,
            )
            if max_def > 0:
                levels = _expand_runs_dev(
                    lbuf, lp["run_out_end"], lp["run_kind"], lp["run_value"], lp["run_bitbase"],
                    n=n, bw=def_bw,
                )
                present = levels == max_def
                rows, lens = _dict_strings_opt_gather(
                    dict_rows, dict_lens, idx, present, n=n, max_len=max_len
                )
                return DeviceColumn(desc, rows, ~present, lens)
            rows = jnp.take(dict_rows, idx, axis=0)
            lens = jnp.take(dict_lens, idx)
            return DeviceColumn(desc, rows, None, lens)

        arrays = ([host_rows, host_lens] if ship_dict else []) + [norm.values_buf, idx_plan]
        if max_def > 0:
            arrays += [norm.levels_buf, lvl_plan]
        return _Staged(arrays, launch)

    def _stage_plain(self, desc, norm: _NormPages) -> "_Staged":
        n = sum(norm.page_n)
        nn = sum(norm.page_nn)
        pt = desc.physical_type
        if pt == Type.BOOLEAN:
            return self._stage_plain_bool(desc, norm, n, nn)
        if pt not in _NP_DTYPE:
            raise _Fallback(f"PLAIN device decode for {Type.name(pt)}")
        width = np.dtype(_NP_DTYPE[pt]).itemsize
        # value streams are already contiguous per page; PLAIN is raw values
        # so the concatenated buffer is contiguous values across pages.
        for i in range(1, len(norm.page_value_base)):
            expected = norm.page_value_base[i - 1] + norm.page_nn[i - 1] * width
            if norm.page_value_base[i] != expected:
                raise _Fallback("non-contiguous PLAIN pages")
        dtype = _JNP_DTYPE[pt]
        f64_as_f32 = False
        if pt == Type.DOUBLE:
            if self.float64_policy == "float32":
                f64_as_f32 = True
            elif self.float64_policy == "bits":
                dtype = jnp.int64
        max_def = desc.max_definition_level
        def_bw = norm.def_bw
        if max_def > 0:
            lvl_plan, _ = _merged_level_plan(norm)

            def launch(dev):
                vbuf, lbuf, lp = dev
                dense, mask = _plain_decode_opt(
                    vbuf, lbuf,
                    lp["run_out_end"], lp["run_kind"], lp["run_value"], lp["run_bitbase"],
                    n=n, nn=nn, dtype=dtype, max_def=max_def,
                    def_bw=def_bw, f64_as_f32=f64_as_f32,
                )
                return DeviceColumn(desc, dense, mask)

            return _Staged([norm.values_buf, norm.levels_buf, lvl_plan], launch)

        def launch(dev):
            (vbuf,) = dev
            dense = _plain_decode_req(vbuf, n=n, dtype=dtype, f64_as_f32=f64_as_f32)
            return DeviceColumn(desc, dense, None)

        return _Staged([norm.values_buf], launch)

    def _stage_plain_bool(self, desc, norm: _NormPages, n, nn) -> "_Staged":
        # Each page's bools are byte-aligned bit-packed: model as one
        # bit-packed "run" per page and reuse the RLE expansion machinery.
        table = np.zeros((len(norm.page_n), 4), dtype=np.int64)
        for i in range(len(norm.page_n)):
            table[i] = (1, norm.page_nn[i], norm.page_value_base[i], 0)
        plan = bitops.run_table_to_device_plan(
            table, nn, bitops.bucket_size(len(table), 4)
        )
        max_def = desc.max_definition_level
        def_bw = norm.def_bw
        lvl_plan = _merged_level_plan(norm)[0] if max_def > 0 else None

        def launch(dev):
            if max_def > 0:
                vbuf, pp, lbuf, lp = dev
            else:
                vbuf, pp = dev
                lbuf = lp = None
            bits = _expand_runs_dev(
                vbuf, pp["run_out_end"], pp["run_kind"], pp["run_value"], pp["run_bitbase"],
                n=nn, bw=1,
            )
            vals = bits.astype(jnp.bool_)
            if max_def > 0:
                levels = _expand_runs_dev(
                    lbuf, lp["run_out_end"], lp["run_kind"], lp["run_value"], lp["run_bitbase"],
                    n=n, bw=def_bw,
                )
                present = levels == max_def
                dense = bitops.dense_scatter(vals, present, fill=False)
                return DeviceColumn(desc, dense, ~present)
            return DeviceColumn(desc, vals, None)

        arrays = [norm.values_buf, plan]
        if max_def > 0:
            arrays += [norm.levels_buf, lvl_plan]
        return _Staged(arrays, launch)

    def _stage_delta(self, desc, norm: _NormPages) -> "_Staged":
        if desc.max_definition_level > 0:
            raise _Fallback("optional delta column (host path)")
        pt = desc.physical_type
        if pt not in (Type.INT32, Type.INT64):
            raise _Fallback("delta for non-int")
        plan = parse_delta_plan(norm.values_buf, _NP_DTYPE[pt])
        if plan is None:
            raise _Fallback("delta needs >32-bit arithmetic")
        n = sum(norm.page_n)
        out_dtype = _JNP_DTYPE[pt]

        def launch(dev):
            vbuf, bitbase, bws, mins = dev
            out = bitops.delta_expand(
                vbuf, bitbase, bws, mins,
                plan["first_value"], n, plan["values_per_miniblock"],
                out_dtype=out_dtype,
            )
            return DeviceColumn(desc, out, None)

        return _Staged(
            [norm.values_buf, plan["mb_bitbase"], plan["mb_bw"], plan["mb_min_delta"]],
            launch,
        )

    def _stage_host(self, chunk, desc) -> "_Staged":
        """Host NumPy decode, shipped dense to the device (correct for every
        chunk the format engine can read)."""
        batch = self.reader.read_column_chunk(chunk)
        dense, mask = batch.dense()
        if isinstance(dense, ByteArrayColumn):
            rows, lengths, _ = _padded_rows(dense)

            def launch(dev):
                if mask is None:
                    drows, dlens = dev
                    return DeviceColumn(desc, drows, None, dlens)
                drows, dlens, dmask = dev
                return DeviceColumn(desc, drows, dmask, dlens)

            arrays = [rows, lengths] + ([] if mask is None else [mask])
            return _Staged(arrays, launch)
        if dense.dtype == np.float64:
            if self.float64_policy == "float32":
                dense = dense.astype(np.float32)
            elif self.float64_policy == "bits":
                dense = dense.view(np.int64)

        def launch(dev):
            if mask is None:
                (dd,) = dev
                return DeviceColumn(desc, dd, None)
            dd, dmask = dev
            return DeviceColumn(desc, dd, dmask)

        return _Staged([dense] + ([] if mask is None else [mask]), launch)


def _count_plain_strings(data_u8: np.ndarray) -> int:
    """Count values in a PLAIN BYTE_ARRAY stream (walk the length chain)."""
    pos = 0
    n = 0
    total = len(data_u8)
    b = data_u8.tobytes()
    while pos < total:
        ln = int.from_bytes(b[pos : pos + 4], "little")
        pos += 4 + ln
        n += 1
    return n
