"""Device-side decode primitives (pure jnp, jit-traceable, TPU-shaped).

The decode hot path is split two-phase (mirroring
``format/encodings/rle_hybrid.py``): the host parses *run tables* (one tiny
entry per run — sequential, byte-granular, cheap) and the device expands
them (vectorized over every output element — the actual O(n) work).  This is
the TPU-native replacement for parquet-mr's per-cell ValuesReader dispatch
(reference seam at ``ParquetReader.java:141-168``; SURVEY.md §2.4 item 2).

Everything here is rank-≥1 vector math — gathers, shifts, cumsums, one
int-matmul — i.e. ops XLA tiles onto the VPU/MXU with static shapes.  The
Pallas kernels in ``tpu/kernels`` specialize the hottest of these; these jnp
forms are the reference they are tested against, and the fallback on CPU.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def _extract_window(data_u8: jax.Array, byte0: jax.Array, shift: jax.Array,
                    bit_width: int) -> jax.Array:
    """5-byte little-endian window at ``byte0`` shifted right by ``shift``
    (0..7), masked to ``bit_width`` bits.  Returns uint32."""
    # gather uint8 first, widen after: widening the whole buffer before the
    # gather would materialize a 4× copy of it in HBM (gather operands do
    # not fuse), which matters when data_u8 is a row-group arena
    g = lambda off: data_u8[byte0 + off].astype(jnp.uint32)
    lo = g(0) | (g(1) << 8) | (g(2) << 16) | (g(3) << 24)
    hi = g(4)
    # (lo >> shift) | (hi << (32 - shift)); shift==0 must not shift hi by 32.
    hi_part = jnp.where(shift == 0, jnp.uint32(0), hi << ((32 - shift) & 31))
    v = (lo >> shift) | hi_part
    mask = jnp.uint32(0xFFFFFFFF) if bit_width == 32 else jnp.uint32((1 << bit_width) - 1)
    return v & mask


def extract_bits(data_u8: jax.Array, bitpos: jax.Array, bit_width: int) -> jax.Array:
    """Gather ``bit_width``-bit little-endian fields at arbitrary bit offsets.

    ``data_u8`` must be padded with ≥8 trailing bytes so the 5-byte window
    never reads out of bounds.  Supports bit_width 1..32; returns uint32.
    """
    if not (1 <= bit_width <= 32):
        raise ValueError(f"bit_width {bit_width} out of range [1, 32]")
    byte0 = (bitpos >> 3).astype(jnp.int32)
    shift = (bitpos & 7).astype(jnp.uint32)
    return _extract_window(data_u8, byte0, shift, bit_width)


def extract_bits_at(data_u8: jax.Array, bytebase: jax.Array, bitoff: jax.Array,
                    bit_width: int) -> jax.Array:
    """:func:`extract_bits` addressed as byte base + *local* bit offset.

    Splitting the address keeps every quantity inside int32 for buffers up
    to 2 GiB: ``bytebase`` is a byte offset (streams start byte-aligned in
    every Parquet encoding) and ``bitoff`` is the within-stream bit
    position, which never approaches 2³¹."""
    if not (1 <= bit_width <= 32):
        raise ValueError(f"bit_width {bit_width} out of range [1, 32]")
    byte0 = (bytebase + (bitoff >> 3)).astype(jnp.int32)
    shift = (bitoff & 7).astype(jnp.uint32)
    return _extract_window(data_u8, byte0, shift, bit_width)


def bit_unpack(data_u8: jax.Array, bit_width: int, count: int) -> jax.Array:
    """Unpack ``count`` contiguous bit-packed values starting at bit 0.

    Bit-matrix formulation: explode bytes to bits, regroup to (count, bw),
    contract with powers of two — an integer matmul XLA maps well.
    Returns int32 (bit_width ≤ 31) — dictionary indices and levels never
    need more.
    """
    if not (1 <= bit_width <= 31):
        raise ValueError(f"bit_width {bit_width} out of range [1, 31]")
    nbytes = (count * bit_width + 7) // 8
    b = jax.lax.slice(data_u8, (0,), (nbytes,)) if data_u8.shape[0] != nbytes else data_u8
    bits = (b[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    bits = bits.reshape(-1)[: count * bit_width].reshape(count, bit_width)
    weights = (jnp.int32(1) << jnp.arange(bit_width, dtype=jnp.int32))
    return jnp.sum(bits.astype(jnp.int32) * weights, axis=1)


def rle_expand(
    data_u8: jax.Array,
    run_out_end: jax.Array,   # int32[R]: cumulative output count after run r
    run_kind: jax.Array,      # int32[R]: 0 = RLE, 1 = bit-packed
    run_value: jax.Array,     # int32[R]: repeated value (RLE runs)
    run_bytebase: jax.Array,  # int32[R]: byte offset of packed data (runs
                              # start byte-aligned per the RLE spec, so a
                              # byte base reaches 2 GiB arenas in int32)
    num_values: int,
    bit_width: int,
) -> jax.Array:
    """Expand an RLE/bit-packed hybrid run table to ``num_values`` int32s.

    Fully vectorized: each output element binary-searches its run
    (``searchsorted``), then either broadcasts the run value or extracts its
    bit field.  Run tables must be padded so R is static; pad runs with
    run_out_end == num_values (they then own no elements).
    """
    out_idx = jnp.arange(num_values, dtype=jnp.int32)
    rid = jnp.searchsorted(run_out_end, out_idx, side="right").astype(jnp.int32)
    rid = jnp.minimum(rid, run_out_end.shape[0] - 1)
    run_start = jnp.where(rid == 0, 0, run_out_end[jnp.maximum(rid - 1, 0)])
    within = out_idx - run_start
    if bit_width == 0:
        return jnp.zeros(num_values, dtype=jnp.int32)
    packed = extract_bits_at(
        data_u8, run_bytebase[rid], within * bit_width, bit_width
    ).astype(jnp.int32)
    return jnp.where(run_kind[rid] == 0, run_value[rid], packed)


def rle_expand_bw(
    data_u8: jax.Array,
    run_out_end: jax.Array,   # int32[R]: cumulative output count after run r
    run_kind: jax.Array,      # int32[R]: 0 = RLE, 1 = bit-packed
    run_value: jax.Array,     # int32[R]: repeated value (RLE runs)
    run_bytebase: jax.Array,  # int32[R]: byte offset of packed data
    run_bw: jax.Array,        # int32[R]: bit width of packed data (may vary!)
    num_values: int,
) -> jax.Array:
    """``rle_expand`` with *per-run* bit widths (all dynamic).

    Writers grow the dictionary index width across pages of one chunk;
    treating width as run data (extract a 32-bit window, mask to the run's
    width) decodes mixed-width chunks in one pass with one compiled shape.
    """
    out_idx = jnp.arange(num_values, dtype=jnp.int32)
    rid = jnp.searchsorted(run_out_end, out_idx, side="right").astype(jnp.int32)
    rid = jnp.minimum(rid, run_out_end.shape[0] - 1)
    run_start = jnp.where(rid == 0, 0, run_out_end[jnp.maximum(rid - 1, 0)])
    within = out_idx - run_start
    bw = run_bw[rid]
    raw = extract_bits_at(data_u8, run_bytebase[rid], within * bw, 32)
    bwu = bw.astype(jnp.uint32)
    mask = jnp.where(
        bw >= 32, jnp.uint32(0xFFFFFFFF), (jnp.uint32(1) << bwu) - jnp.uint32(1)
    )
    mask = jnp.where(bw == 0, jnp.uint32(0), mask)
    packed = (raw & mask).astype(jnp.int32)
    return jnp.where(run_kind[rid] == 0, run_value[rid], packed)


def dict_gather(dictionary: jax.Array, indices: jax.Array) -> jax.Array:
    """The dictionary gather — one ``take`` on device (north-star hot op)."""
    return jnp.take(dictionary, indices, axis=0)


def dense_scatter(values: jax.Array, present: jax.Array, fill=0) -> jax.Array:
    """Spread non-null ``values`` into dense row slots given a present mask.

    ``values`` length may exceed the count of present slots (padding);
    surplus is ignored.  Vectorized: prefix-sum the mask for the gather map.
    """
    if values.shape[0] == 0:  # all-null column: nothing to gather
        shape = (present.shape[0],) + values.shape[1:]
        return jnp.full(shape, fill, dtype=values.dtype)
    value_index = jnp.cumsum(present.astype(jnp.int32)) - 1
    value_index = jnp.clip(value_index, 0, values.shape[0] - 1)
    dense = jnp.take(values, value_index, axis=0)
    fill_arr = jnp.asarray(fill, dtype=dense.dtype)
    if dense.ndim > 1:
        pmask = present[:, None]
    else:
        pmask = present
    return jnp.where(pmask, dense, fill_arr)


def bitcast_bytes(data_u8: jax.Array, dtype, count: int) -> jax.Array:
    """Reinterpret a little-endian byte buffer as ``count`` fixed-width values
    (device-side PLAIN decode)."""
    dtype = jnp.dtype(dtype)
    width = dtype.itemsize
    words = jax.lax.slice(data_u8, (0,), (count * width,)).reshape(count, width)
    return jax.lax.bitcast_convert_type(words, dtype).reshape(count)


def unpack_bools(data_u8: jax.Array, count: int) -> jax.Array:
    """PLAIN BOOLEAN: LSB-first bit unpack to bool[count]."""
    bits = (data_u8[: (count + 7) // 8, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return bits.reshape(-1)[:count].astype(jnp.bool_)


def _combine64(lo: jax.Array, hi: jax.Array) -> jax.Array:
    """Recombine an int64 split into (low, high) int32 rows (the int32
    plan slab cannot carry 64-bit constants directly)."""
    return lo.astype(jnp.uint32).astype(jnp.int64) | (hi.astype(jnp.int64) << 32)


def extract_bits64(data_u8: jax.Array, bytebase: jax.Array, bitoff: jax.Array,
                   bw: jax.Array) -> jax.Array:
    """Gather variable-width fields up to 64 bits (two 32-bit windows) at
    byte base + local bit offset (int32-safe to 2 GiB, as
    :func:`extract_bits_at`).

    ``bw`` is a per-element int32 array in [0, 64]; returns int64 with the
    packed value zero-extended (bits ≥ bw masked off)."""
    lo = extract_bits_at(data_u8, bytebase, bitoff, 32).astype(jnp.int64)
    hi = extract_bits_at(data_u8, bytebase, bitoff + 32, 32).astype(jnp.int64)
    v = lo | (hi << 32)
    bw64 = bw.astype(jnp.uint64)
    mask = jnp.where(
        bw >= 64,
        jnp.uint64(0xFFFFFFFFFFFFFFFF),
        (jnp.uint64(1) << jnp.clip(bw64, 0, 63)) - jnp.uint64(1),
    )
    mask = jnp.where(bw <= 0, jnp.uint64(0), mask)
    return (v.astype(jnp.uint64) & mask).astype(jnp.int64)


def delta_expand_wide(
    data_u8: jax.Array,
    mb_bytebase: jax.Array,   # int32[M]: byte offset of each miniblock
                              # (miniblocks hold 32·k values → whole bytes)
    mb_bw: jax.Array,         # int32[M] (≤ 64)
    mb_min_lo: jax.Array,     # int32[M]: min_delta low word
    mb_min_hi: jax.Array,     # int32[M]: min_delta high word
    first_lo, first_hi,       # scalars (int32 words)
    num_values: int,
    values_per_miniblock: int,
) -> jax.Array:
    """DELTA_BINARY_PACKED expansion in full int64 arithmetic: miniblock
    widths up to 64 bits and prefix sums beyond int32 range (timestamps,
    row ids).  Wraparound at 64 bits is the spec's own semantics, so no
    range bound exists to enforce."""
    first = _combine64(jnp.asarray(first_lo, jnp.int32), jnp.asarray(first_hi, jnp.int32))
    n_deltas = num_values - 1
    if n_deltas <= 0:
        return jnp.full((max(num_values, 1),), 0, jnp.int64)[:num_values] + first
    idx = jnp.arange(n_deltas, dtype=jnp.int32)
    mb = idx // values_per_miniblock
    within = idx % values_per_miniblock
    bw = mb_bw[mb]
    packed = extract_bits64(data_u8, mb_bytebase[mb], within * bw, bw)
    deltas = packed + _combine64(mb_min_lo, mb_min_hi)[mb]
    acc = jnp.cumsum(deltas) + first
    return jnp.concatenate([first[None], acc])


def delta_expand_paged_wide(
    data_u8: jax.Array,
    mb_out_start: jax.Array,  # int32[M]
    mb_bytebase: jax.Array,   # int32[M]: byte offset of each miniblock
    mb_bw: jax.Array,         # int32[M] (≤ 64)
    mb_min_lo: jax.Array,     # int32[M]
    mb_min_hi: jax.Array,     # int32[M]
    page_start: jax.Array,    # int32[P]
    page_first_lo: jax.Array,  # int32[P]
    page_first_hi: jax.Array,  # int32[P]
    page_cum: jax.Array,      # int32[P]
    num_values: int,
) -> jax.Array:
    """The segmented (multi-page / optional) form of
    :func:`delta_expand_wide` — same int64 reconstruction as
    :func:`delta_expand_paged`'s int32 one."""
    i = jnp.arange(num_values, dtype=jnp.int32)
    pgi = jnp.searchsorted(page_cum, i, side="right").astype(jnp.int32)
    pgi = jnp.minimum(pgi, page_cum.shape[0] - 1)
    s = page_start[pgi]
    mb = jnp.searchsorted(mb_out_start, i, side="right").astype(jnp.int32) - 1
    mb = jnp.clip(mb, 0, mb_out_start.shape[0] - 1)
    within = i - mb_out_start[mb]
    bw = mb_bw[mb]
    packed = extract_bits64(
        data_u8, mb_bytebase[mb], jnp.maximum(within * bw, 0), bw
    )
    delta = packed + _combine64(mb_min_lo, mb_min_hi)[mb]
    d0 = jnp.where(i == s, jnp.int64(0), delta)
    c0 = jnp.cumsum(d0)
    c0_at_start = jnp.take(c0, jnp.clip(s, 0, num_values - 1))
    first = _combine64(page_first_lo, page_first_hi)[pgi]
    return first + c0 - c0_at_start


def delta_expand(
    data_u8: jax.Array,
    mb_bytebase: jax.Array,   # int32[M]: byte offset of each miniblock
    mb_bw: jax.Array,         # int32[M]: bit width of each miniblock
    mb_min_delta: jax.Array,  # int32[M]: min_delta of the owning block
    first_value,              # scalar
    num_values: int,
    values_per_miniblock: int,
    out_dtype=jnp.int32,
) -> jax.Array:
    """DELTA_BINARY_PACKED expansion for ≤32-bit miniblock widths.

    Per-delta variable bit width is handled by gathering each element's
    width/base, then extracting a 32-bit window and masking to its width.
    Reconstruction is first + cumsum(min_delta + packed), in 32-bit
    wraparound (int64 columns with wider dynamics fall back to host decode).
    """
    n_deltas = num_values - 1
    if n_deltas <= 0:
        return jnp.full((max(num_values, 1),), first_value, dtype=out_dtype)[:num_values]
    idx = jnp.arange(n_deltas, dtype=jnp.int32)
    mb = idx // values_per_miniblock
    within = idx % values_per_miniblock
    bw = mb_bw[mb]
    raw = extract_bits_at(data_u8, mb_bytebase[mb], within * bw, 32)
    mask = jnp.where(
        bw >= 32,
        jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << bw.astype(jnp.uint32)) - jnp.uint32(1),
    )
    mask = jnp.where(bw == 0, jnp.uint32(0), mask)
    packed = (raw & mask).astype(jnp.int32)
    deltas = packed + mb_min_delta[mb]
    acc = jnp.cumsum(deltas.astype(jnp.int32)) + jnp.asarray(first_value, jnp.int32)
    out = jnp.concatenate([jnp.asarray([first_value], dtype=jnp.int32), acc])
    return out.astype(out_dtype)


def delta_expand_paged(
    data_u8: jax.Array,
    mb_out_start: jax.Array,  # int32[M]: global value index of each miniblock's first delta
    mb_bytebase: jax.Array,   # int32[M]: byte offset of each miniblock
    mb_bw: jax.Array,         # int32[M]: bit width of each miniblock
    mb_min_delta: jax.Array,  # int32[M]: min_delta of the owning block
    page_start: jax.Array,    # int32[P]: global value index of each page's first value
    page_first: jax.Array,    # int32[P]: each page's first_value
    page_cum: jax.Array,      # int32[P]: cumulative value count after each page
    num_values: int,
) -> jax.Array:
    """DELTA_BINARY_PACKED expansion across several independent page
    streams (each with its own header/first value), fully vectorized.

    Segmented reconstruction: build a delta array D0 that is 0 at page
    starts and the decoded delta elsewhere; one global cumsum C0 then
    gives value[i] = first[page(i)] + C0[i] - C0[start(page(i))].
    All arithmetic is int32 wraparound (hosts range-check before choosing
    this path for 64-bit columns).
    """
    i = jnp.arange(num_values, dtype=jnp.int32)
    pgi = jnp.searchsorted(page_cum, i, side="right").astype(jnp.int32)
    pgi = jnp.minimum(pgi, page_cum.shape[0] - 1)
    s = page_start[pgi]
    # miniblock of each position (positions at page starts take garbage
    # miniblock data; masked to zero below)
    mb = jnp.searchsorted(mb_out_start, i, side="right").astype(jnp.int32) - 1
    mb = jnp.clip(mb, 0, mb_out_start.shape[0] - 1)
    within = i - mb_out_start[mb]
    bw = mb_bw[mb]
    raw = extract_bits_at(
        data_u8, mb_bytebase[mb], jnp.maximum(within * bw, 0), 32
    )
    mask = jnp.where(
        bw >= 32,
        jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << jnp.clip(bw, 0, 31).astype(jnp.uint32)) - jnp.uint32(1),
    )
    mask = jnp.where(bw <= 0, jnp.uint32(0), mask)
    delta = (raw & mask).astype(jnp.int32) + mb_min_delta[mb]
    d0 = jnp.where(i == s, jnp.int32(0), delta)
    c0 = jnp.cumsum(d0, dtype=jnp.int32)
    c0_at_start = jnp.take(c0, jnp.clip(s, 0, num_values - 1))
    return page_first[pgi] + c0 - c0_at_start


# ---------------------------------------------------------------------------
# Host-side plan builders (NumPy; produce the arrays the device ops consume)
# ---------------------------------------------------------------------------

class PlanOverflow(ValueError):
    """A run table cannot be expressed in int32 device plans (offsets past
    2 GiB or a single bit-packed run past 2³¹ bits) — callers with a host
    decode path should fall back instead of failing."""


class PlanPadExceeded(ValueError):
    """A plan needs more rows than the padded capacity offered; ``needed``
    carries the exact row count so callers re-size in one retry."""

    def __init__(self, needed: int, pad_runs: int):
        super().__init__(f"run tables ({needed}) exceed padding ({pad_runs})")
        self.needed = needed


def run_table_to_device_plan(run_table: np.ndarray, num_values: int, pad_runs: int):
    """Convert a ``parse_runs`` table into padded device-ready arrays.

    Returns dict of numpy arrays: run_out_end, run_kind, run_value,
    run_bytebase — each padded to ``pad_runs`` entries.
    """
    r = len(run_table)
    if r > pad_runs:
        raise ValueError(f"run table ({r}) exceeds padding ({pad_runs})")
    out_end = np.full(pad_runs, num_values, dtype=np.int32)
    kind = np.zeros(pad_runs, dtype=np.int32)
    value = np.zeros(pad_runs, dtype=np.int32)
    bytebase = np.zeros(pad_runs, dtype=np.int32)
    if r:
        counts = run_table[:, 1]
        out_end[:r] = np.cumsum(counts)
        kind[:r] = run_table[:, 0]
        is_bp = run_table[:, 0] == 1
        value[:r] = np.where(is_bp, 0, run_table[:, 2]).astype(np.int32)
        if run_table[is_bp, 2].max(initial=0) >= 2**31:
            raise PlanOverflow("byte offsets exceed int32 (arena too large)")
        if int(run_table[is_bp, 1].max(initial=0)) * 32 >= 2**31:
            # within-run bit positions (within * bit_width) must stay int32
            raise PlanOverflow("bit-packed run too long for device decode")
        bytebase[:r] = np.where(is_bp, run_table[:, 2], 0).astype(np.int32)
    return {
        "run_out_end": out_end,
        "run_kind": kind,
        "run_value": value,
        "run_bytebase": bytebase,
    }


def tables_to_plan5(tables, total: int, pad_runs: int) -> np.ndarray:
    """Merge ``parse_runs`` tables into one flat int32 plan of 5 rows ×
    ``pad_runs``: out_end, kind, value, bytebase, bw.

    ``tables`` is a sequence of (run_table, bit_width) pairs whose byte
    offsets (column 2 of bit-packed rows) are already absolute in the target
    buffer.  Pad runs own no output (out_end == total).
    """
    live = [(t, bw) for t, bw in tables if len(t)]
    r = sum(len(t) for t, _ in live)
    if r > pad_runs:
        raise ValueError(f"run tables ({r}) exceed padding ({pad_runs})")
    plan = np.zeros((5, pad_runs), dtype=np.int32)
    plan[0] = total
    if live:
        # one pass over the concatenation instead of per-table slices —
        # a chunk has one table per page, and staging builds thousands
        cat = np.concatenate([t for t, _ in live], axis=0)
        bws = np.repeat(
            np.fromiter((bw for _, bw in live), np.int64, len(live)),
            np.fromiter((len(t) for t, _ in live), np.int64, len(live)),
        )
        is_bp = cat[:, 0] == 1
        if cat[is_bp, 2].max(initial=0) >= 2**31:
            raise PlanOverflow("byte offsets exceed int32 (arena too large)")
        if (cat[is_bp, 1] * bws[is_bp]).max(initial=0) >= 2**31:
            # within-run bit positions must also stay int32
            raise PlanOverflow("bit-packed run too long for device decode")
        plan[1, :r] = cat[:, 0]
        plan[2, :r] = np.where(is_bp, 0, cat[:, 2]).astype(np.int32)
        plan[3, :r] = np.where(is_bp, cat[:, 2], 0).astype(np.int32)
        plan[4, :r] = bws
        out_end = np.cumsum(cat[:, 1])
        if out_end[-1] != total:
            # trailing pad already holds `total`; runs must sum to it
            raise ValueError(
                f"run counts sum to {out_end[-1]}, expected {total}"
            )
        plan[0, :r] = out_end
    return plan.reshape(-1)


def plan5_from_streams(data, streams, total: int, pad_runs: int):
    """Build the flat 5×pad int32 plan for many (pos, count, bw) streams
    of one buffer — the fast form of ``parse_runs_batch`` +
    :func:`tables_to_plan5` (one native pass, no intermediate tables).

    A stream with bw == 0 contributes one synthetic RLE run of zeros (the
    dictionary zero-width page; plan bw row 0, matching the native path).
    Returns (plan, rows_used); raises :class:`PlanOverflow` when int32
    limits are exceeded and :class:`PlanPadExceeded` (carrying the exact
    row count) when ``pad_runs`` is too small."""
    try:
        from ..native import binding as _nb
    except ImportError:  # pragma: no cover - native lib is optional
        _nb = None
    if _nb is not None and _nb.available():
        try:
            return _nb.rle_plan5_batch(
                data,
                [p for p, _, _ in streams],
                [c for _, c, _ in streams],
                [b for _, _, b in streams],
                total, pad_runs,
            )
        except _nb.PlanOverflowNative as e:
            raise PlanOverflow(str(e)) from None
        except _nb.PlanPadExceeded as e:
            raise PlanPadExceeded(e.needed, pad_runs) from None
    from ..format.encodings import rle_hybrid as e_rle

    tables = []
    for p, c, b in streams:
        if b == 0:
            tables.append((np.array([[0, c, 0, 0]], dtype=np.int64), 0))
        else:
            tables.append((e_rle.parse_runs(data, c, b, pos=p)[0], b))
    r = sum(len(t) for t, _ in tables)
    if r > pad_runs:
        raise PlanPadExceeded(r, pad_runs)
    return tables_to_plan5(tables, total, pad_runs), r


def pad_to(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    """Pad a 1-D array up to ``size`` (static-shape friendliness)."""
    if len(arr) > size:
        raise ValueError(f"array ({len(arr)}) longer than pad target ({size})")
    if len(arr) == size:
        return arr
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def bucket_size(n: int, minimum: int = 1024) -> int:
    """Round up to the next power of two (jit-cache-friendly shape buckets)."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()
