"""Fused device ENCODE programs — the write-path mirror of the fused
decode launch (docs/write.md).

One row group encodes in (at most) two fused launches, both dispatched
through the persistent executable cache (:mod:`.exec_cache`):

* **analyze** — everything whose output shape is data-independent:
  dictionary build (bit-pattern sort → unique flags → cumsum ranks →
  scatter, yielding the per-value index stream, the distinct count, and
  the first-sorted-occurrence positions the host gathers dictionary
  VALUES from), DELTA_BINARY_PACKED preparation (wrapped deltas, the
  signed global ``min_delta``, offset stream, max offset), and
  BYTE_STREAM_SPLIT (per-page byte transposition — no dynamic inputs,
  so it finishes in this launch).
* **pack** — bit-packing of index/offset streams at a STATIC width the
  host chose from the analyze scalars (dict count → index width, max
  offset → delta width).  Widths are restricted to divisors of 32 so a
  32-bit word holds a whole number of values: the pack is a reshape +
  shift + OR fold, no scatter.  Any width the spec allows (1..32) is
  legal on the wire — padding up to a divisor of 32 costs bytes the
  downstream page compression largely reclaims, and buys a fused
  word-parallel pack.

Everything here is XLA-level (``jnp``) like the decode engine's fusion
wrapper — sort/cumsum/scatter/shift lower to single fused executables;
the per-column loop is unrolled at trace time exactly like
``_decode_fused``.  Bit order matches the parquet RLE/bit-packed hybrid
(LSB-first, value *j* of a word at bits ``[j*w, (j+1)*w)``, words
little-endian) — pinned against ``rle_hybrid.bit_pack`` by test.

The program tuple (:class:`EncSpec` per column) is the static jit
signature and therefore the exec-cache key; column names deliberately
stay OUT of the spec so two files with the same shape signature share
one executable.
"""

from __future__ import annotations

from functools import partial, reduce
from typing import List, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..utils import trace
from . import exec_cache

#: pack widths a 32-bit word divides evenly into (module docstring)
PACK_WIDTHS = (1, 2, 4, 8, 16, 32)


def pack_width_for(min_width: int) -> int:
    """Smallest legal pack width >= ``min_width`` (>=1), or 0 when the
    stream needs no bits at all (single-value dictionaries, all-equal
    deltas)."""
    if min_width <= 0:
        return 0
    for w in PACK_WIDTHS:
        if w >= min_width:
            return w
    raise ValueError(f"bit width {min_width} exceeds 32")


class EncSpec(NamedTuple):
    """Static per-column signature of one fused encode launch.

    ``kind``: ``dict`` | ``delta`` | ``bss`` (analyze) or ``pack``
    (pack launch).  ``dtype`` is the UNSIGNED bit-view dtype of the
    value stream the host ships (floats arrive bit-viewed — sort order
    is irrelevant for dictionary identity, only equal-bits adjacency).
    ``n`` is the exact element count of the input array (the write path
    ships exact host arrays; shape buckets are a decode-side concern).
    ``page_rows`` (bss only) is the static page cut the per-page
    transposition honors; ``width`` (pack only) is the static bit
    width."""

    kind: str
    dtype: str
    n: int
    page_rows: int = 0
    width: int = 0


_SIGNED = {"uint32": jnp.int32, "uint64": jnp.int64}


def _dict_build(keys, n: int):
    """Sorted-unique dictionary build: returns (indices uint32, count
    int32 scalar, uniq_pos int32 — original position of each distinct
    value, in dictionary order)."""
    order = jnp.argsort(keys)  # stable: equal bits keep input order
    sk = keys[order]
    new = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sk[1:] != sk[:-1]]
    )
    ranks = jnp.cumsum(new.astype(jnp.int32)) - 1
    count = ranks[-1] + 1
    indices = (
        jnp.zeros(n, jnp.uint32).at[order].set(ranks.astype(jnp.uint32))
    )
    # representative original position per distinct value: min() makes
    # the pick deterministic under duplicate scatter indices (the first
    # occurrence in sorted order — every candidate holds equal bits, so
    # ANY pick yields the same dictionary bytes; determinism is for
    # bit-identical re-runs)
    uniq_pos = (
        jnp.full(n, n, jnp.int32).at[ranks].min(order.astype(jnp.int32))
    )
    return indices, count.astype(jnp.int32), uniq_pos


def _delta_analyze(vu, spec: EncSpec):
    """Wrapped deltas → (offsets unsigned, min_delta signed scalar,
    max_offset unsigned scalar).  Offsets are ``delta - min_delta`` at
    the column's physical width (wrapping, spec semantics) with ONE
    global min shared by every block — each block header re-declares
    it, which is legal and keeps the packed stream contiguous."""
    signed = _SIGNED[spec.dtype]
    if spec.n <= 1:
        z = jnp.zeros((), vu.dtype)
        return (
            jnp.zeros((0,), vu.dtype),
            jnp.zeros((), signed),
            z,
        )
    d = vu[1:] - vu[:-1]
    sd = jax.lax.bitcast_convert_type(d, signed)
    min_d = jnp.min(sd)
    offs = d - jax.lax.bitcast_convert_type(min_d, vu.dtype)
    return offs, min_d, jnp.max(offs)


def _byte_split(v):
    """(n,) unsigned → (n, itemsize) little-endian bytes."""
    isz = v.dtype.itemsize
    return jnp.stack(
        [(v >> jnp.asarray(8 * k, v.dtype)).astype(jnp.uint8)
         for k in range(isz)],
        axis=1,
    )


def _bss_split(v, spec: EncSpec):
    """Per-page BYTE_STREAM_SPLIT: full pages transpose as one block,
    the partial tail page transposes on its own (a short page's stream
    is NOT a slice of the full-page transpose)."""
    b = _byte_split(v)
    isz = v.dtype.itemsize
    p = spec.page_rows
    k_full = spec.n // p
    full = (
        b[: k_full * p].reshape(k_full, p, isz)
        .transpose(0, 2, 1).reshape(-1)
    )
    tail = b[k_full * p:].T.reshape(-1)
    return full, tail


@partial(jax.jit, static_argnums=(0,))
def _encode_analyze(program: Tuple[EncSpec, ...], *arrays):
    """The fused per-row-group ANALYZE launch (module docstring): one
    input array per spec, outputs concatenated in spec order — dict →
    (indices, count, uniq_pos), delta → (offsets, min_delta, max_off),
    bss → (full_pages_bytes, tail_bytes)."""
    outs: list = []
    for i, spec in enumerate(program):
        v = arrays[i]
        if spec.kind == "dict":
            outs.extend(_dict_build(v, spec.n))
        elif spec.kind == "delta":
            outs.extend(_delta_analyze(v, spec))
        elif spec.kind == "bss":
            outs.extend(_bss_split(v, spec))
        else:  # pragma: no cover - specs are engine-built
            raise ValueError(f"bad analyze kind {spec.kind!r}")
    return tuple(outs)


def _pack_stream(arr, spec: EncSpec):
    """Bit-pack ``spec.n`` values at static width ``spec.width`` into
    LSB-first bytes (parquet hybrid bit-packed layout)."""
    w = spec.width
    v = arr.astype(jnp.uint32)
    per = 32 // w
    m = -(-spec.n // per)
    v = jnp.pad(v, (0, m * per - spec.n)).reshape(m, per)
    words = reduce(
        jnp.bitwise_or,
        [v[:, j] << jnp.uint32(j * w) for j in range(per)],
    )
    return jnp.stack(
        [(words >> jnp.uint32(8 * k)).astype(jnp.uint8) for k in range(4)],
        axis=1,
    ).reshape(-1)


@partial(jax.jit, static_argnums=(0,))
def _encode_pack(program: Tuple[EncSpec, ...], *arrays):
    """The fused PACK launch: every index/offset stream of the row
    group bit-packs in one executable (one output per spec)."""
    return tuple(
        _pack_stream(arr, spec) for spec, arr in zip(program, arrays)
    )


def run_analyze(program: Tuple[EncSpec, ...], arrays: List, device=None):
    """Dispatch one fused analyze launch through the exec cache."""
    trace.count("write.launches")
    return exec_cache.dispatch(
        _encode_analyze, (program,), arrays, device=device
    )


def run_pack(program: Tuple[EncSpec, ...], arrays: List, device=None):
    """Dispatch one fused pack launch through the exec cache."""
    trace.count("write.launches")
    return exec_cache.dispatch(
        _encode_pack, (program,), arrays, device=device
    )
