"""Fixed-shape re-batching — ragged row groups in, exact ``batch_size``
rows out (``docs/data.md``).

Decoded row groups are ragged (whatever the writer chose); a training
step wants static shapes.  :class:`RowBuffer` is the carry-over buffer
that bridges them: decoded groups (already window-shuffled — the TPU
engine fuses each unit's permutation into its decode via ``out_perm``,
and the host face applies it with :func:`permute_parts`) push per-column
segments in, and rows come out either eagerly (``take`` — the host
face's NumPy path, where slicing is cheap) or as LAZY windows
(``take_windows`` — the device face's path): ``(segment, start, stop)``
references that :func:`fused_assemble` turns into finished batches in
**one** compiled call.  Eager ``jax.numpy`` would pay one dispatch per
slice/concat/pad per column — ~50 dispatches per batch of a 16-column
file, which dominates the loader wall on every backend's dispatch path;
the fused form pays one per *group's worth of ready batches*
(``split``), not one per array op.

String columns are padded ``(n, W)`` byte rows + lengths.  ``W`` is a
per-column high-water mark shared across the whole loader run (the
engine's monotone-bucket discipline applied to batch shapes): widths
only grow, and the checkpoint carries them, so a resumed run emits
bit-identical shapes.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..batch.columns import BatchColumn
from ..format.schema import ColumnDescriptor

# one column's rows in transit: (values, mask, lengths) — mask/lengths
# None when the column is required / not strings
Part = Tuple[object, Optional[object], Optional[object]]
# a lazy reference to rows [start, stop) of a buffered Part
Window = Tuple[Part, int, int]


@dataclass(frozen=True)
class ColumnSpec:
    """Static per-column facts the batcher needs (fixed at loader
    construction: the schema is the dataset contract)."""

    name: str
    descriptor: ColumnDescriptor
    is_string: bool
    has_mask: bool
    f64_bits: bool = False


def slice_part(part: Part, a: int, b: int) -> Part:
    v, m, ln = part
    return (
        v[a:b],
        m[a:b] if m is not None else None,
        ln[a:b] if ln is not None else None,
    )


def permute_parts(parts: Sequence[Part], idx) -> List[Part]:
    """Apply one row permutation to every column — the host face's
    eager window shuffle (the device face fuses the same permutation
    into the decode executable instead)."""
    return [
        (
            v[idx],
            m[idx] if m is not None else None,
            ln[idx] if ln is not None else None,
        )
        for v, m, ln in parts
    ]


def grow_widths(specs: Sequence[ColumnSpec], parts: Sequence[Part],
                widths: Dict[str, int]) -> None:
    """Fold one group's string widths into the shared high-water marks
    (every decoded group passes through here, on either emit path)."""
    for spec, (v, _m, _l) in zip(specs, parts):
        if spec.is_string:
            w = int(v.shape[1]) if getattr(v, "ndim", 1) == 2 else 0
            if w > widths.get(spec.name, 0):
                widths[spec.name] = w


@dataclass
class RowBuffer:
    """Multi-column carry-over buffer; all columns advance in lockstep
    (segments are pushed and split together, so row alignment can never
    drift between columns).  Splits are bookkeeping only — a segment's
    arrays are never sliced until consumption."""

    specs: Sequence[ColumnSpec]
    xp: object
    widths: Dict[str, int]  # shared string-width HWMs (loader-owned)
    _segs: deque = field(default_factory=deque)  # (n_rows, [Part], offset)
    rows: int = 0

    def push(self, parts: Sequence[Part], n: int, skip: int = 0) -> None:
        if n - skip <= 0:
            return
        grow_widths(self.specs, parts, self.widths)
        self._segs.append((n - skip, list(parts), skip))
        self.rows += n - skip

    def _consume(self, n: int) -> List[Tuple[List[Part], int, int]]:
        """Pop ``n`` rows as (segment parts, start, stop) windows."""
        if n > self.rows:
            raise ValueError(f"take({n}) from buffer of {self.rows} rows")
        out = []
        got = 0
        while got < n:
            sn, parts, off = self._segs.popleft()
            need = n - got
            used = min(sn, need)
            out.append((parts, off, off + used))
            if used < sn:
                self._segs.appendleft((sn - used, parts, off + used))
            got += used
        self.rows -= n
        return out

    def take_windows(self, n: int) -> List[List[Window]]:
        """Exactly ``n`` rows per column as LAZY windows — no array op
        happens here; :func:`fused_assemble` materializes them in one
        compiled call."""
        segs = self._consume(n)
        return [
            [(parts[ci], a, b) for parts, a, b in segs]
            for ci in range(len(self.specs))
        ]

    def take(self, n: int) -> List[Part]:
        """Exactly ``n`` rows per column, materialized eagerly (the host
        NumPy path; strings padded to the current width HWM)."""
        segs = self._consume(n)
        pieces: List[List[Part]] = [
            [slice_part(parts[ci], a, b) for parts, a, b in segs]
            for ci in range(len(self.specs))
        ]
        return [
            self._join(spec, ps) for spec, ps in zip(self.specs, pieces)
        ]

    def _pad_width(self, v, w: int):
        if int(v.shape[1]) == w:
            return v
        return self.xp.pad(v, ((0, 0), (0, w - int(v.shape[1]))))

    def _join(self, spec: ColumnSpec, ps: List[Part]) -> Part:
        xp = self.xp
        if spec.is_string:
            w = self.widths.get(spec.name, 0)
            vs = [self._pad_width(p[0], w) for p in ps]
        else:
            vs = [p[0] for p in ps]
        v = vs[0] if len(vs) == 1 else xp.concatenate(vs)
        m = None
        if ps[0][1] is not None:
            ms = [p[1] for p in ps]
            m = ms[0] if len(ms) == 1 else xp.concatenate(ms)
        ln = None
        if ps[0][2] is not None:
            ls = [p[2] for p in ps]
            ln = ls[0] if len(ls) == 1 else xp.concatenate(ls)
        return (v, m, ln)


# The batch-shaping jits dispatch through tpu.exec_cache: their static
# structure (piece layout, widths, pad, split) plus input avals key the
# PERSISTENT executable cache, so a warm process stops recompiling its
# batch shapes (docs/perf.md — the PR 8 follow-on).  Group-aligned
# batch sizes keep the signature set tiny; misaligned ones cycle
# through more shapes (each a one-time compile per toolchain, exactly
# like the fused decode programs).


def _jit_split(strct: tuple, kk: int, *arrs):
    import jax.numpy as jnp
    from jax import lax

    out = []
    i = 0
    for is_str, w, (hm, hl) in strct:
        v = arrs[i]
        i += 1
        if is_str and int(v.shape[1]) != w:
            v = jnp.pad(v, ((0, 0), (0, w - int(v.shape[1]))))
        m = arrs[i] if hm else None
        i += 1 if hm else 0
        ln = arrs[i] if hl else None
        i += 1 if hl else 0
        B = v.shape[0] // kk
        for j in range(kk):
            out.append((
                lax.slice_in_dim(v, j * B, (j + 1) * B),
                None if m is None
                else lax.slice_in_dim(m, j * B, (j + 1) * B),
                None if ln is None
                else lax.slice_in_dim(ln, j * B, (j + 1) * B),
            ))
    return tuple(out)


_SPLIT_JIT = None
_FUSE_JIT = None
# bound on RETAINED compiled batch shapes (the old per-key dict's 256
# cap, kept): misaligned batch sizes cycle through many signatures, and
# jax's own per-function jit cache never evicts — past the cap both
# functions' traces clear so dead executables can be collected (the
# persistent exec cache, when active, makes the re-compile a disk load)
_SEEN_SIGS: set = set()
_MAX_SIGS = 256


def _note_sig(key) -> None:
    _SEEN_SIGS.add(key)
    if len(_SEEN_SIGS) > _MAX_SIGS:
        _SEEN_SIGS.clear()
        for fn in (_SPLIT_JIT, _FUSE_JIT):
            if fn is not None:
                fn.clear_cache()


def _converge_leaves(leaves: list) -> list:
    """Pin every jax-array leaf to the default device before a fused
    dispatch.  Under the multi-chip scan mesh (docs/multichip.md) one
    assemble call can cover windows from groups decoded on DIFFERENT
    devices — jit rejects mixed-device arguments, and a cached
    executable must always meet its inputs on one stable device — so
    the batcher is the convergence point.  No-op (no copies, same list)
    when everything already sits on the default device, i.e. whenever
    the mesh is off."""
    import jax

    tgt = jax.local_devices()[0]
    seen: set = set()
    for a in leaves:
        if isinstance(a, jax.Array):
            seen.update(a.devices())
    if not seen or seen == {tgt}:
        return leaves
    return [
        jax.device_put(a, tgt) if isinstance(a, jax.Array) else a
        for a in leaves
    ]


def _split_jit():
    global _SPLIT_JIT
    if _SPLIT_JIT is None:
        import jax

        _SPLIT_JIT = jax.jit(_jit_split, static_argnums=(0, 1))
    return _SPLIT_JIT


def aligned_split(specs: Sequence[ColumnSpec], parts: Sequence[Part],
                  widths: Dict[str, int], k: int) -> List[List[Part]]:
    """Cut one decoded group straight into ``k`` equal batches in one
    compiled dispatch — the GROUP-ALIGNED fast path the loader takes
    when the carry buffer is empty and the group's rows divide evenly
    by ``batch_size``.

    Unlike :func:`fused_assemble` there are no traced offsets and no
    concatenation: every cut is a static ``slice_in_dim``, which XLA
    turns into plain contiguous copies (measured ~2x cheaper than the
    dynamic-sliced general form).  Pick a batch size that divides the
    writer's row-group size and every steady-state group rides this
    path; misaligned groups fall back to the carry buffer seamlessly.
    """
    from ..tpu import exec_cache

    leaves: list = []
    sig = []
    for spec, (v, m, ln) in zip(specs, parts):
        w = widths.get(spec.name, 0) if spec.is_string else 0
        leaves.append(v)
        if m is not None:
            leaves.append(m)
        if ln is not None:
            leaves.append(ln)
        sig.append((bool(spec.is_string), int(w),
                    (m is not None, ln is not None)))
    _note_sig((
        "split", tuple(sig), int(k),
        tuple((a.shape, str(a.dtype)) for a in leaves),
    ))
    flat = exec_cache.dispatch(
        _split_jit(), (tuple(sig), int(k)), _converge_leaves(leaves)
    )
    # flat is column-major: per column, k consecutive batch parts
    return [
        [flat[ci * k + j] for ci in range(len(specs))] for j in range(k)
    ]


def fused_assemble(specs: Sequence[ColumnSpec],
                   windows: List[List[Window]],
                   widths: Dict[str, int],
                   pad: int = 0, split: int = 1) -> List[List[Part]]:
    """Materialize ``split`` consecutive equal-size batches in ONE
    compiled call; returns ``split`` per-column part lists.

    Per column, the windows slice out of their source segments
    (``dynamic_slice`` — traced starts, static sizes), strings pad to
    the width HWM, pieces concatenate, ``pad`` zero rows append (the
    pad-remainder policy, ``split == 1`` only), and the result cuts into
    ``split`` equal static slices.  Eagerly that is ~3 dispatches per
    column per batch; fused it is one dispatch per call — and the call
    covers every batch a decoded group completed, so the device sees one
    executable per group, not per batch.
    """
    from ..tpu import exec_cache

    if pad and split != 1:
        raise ValueError("pad only applies to a single (tail) batch")
    leaves: list = []
    starts: List[int] = []
    sig = []
    for spec, ws in zip(specs, windows):
        w = widths.get(spec.name, 0) if spec.is_string else 0
        flags = []
        for (v, m, ln), a, b in ws:
            flags.append((m is not None, ln is not None, b - a))
            starts.append(a)
            leaves.append(v)
            if m is not None:
                leaves.append(m)
            if ln is not None:
                leaves.append(ln)
        sig.append((bool(spec.is_string), int(w), tuple(flags)))
    _note_sig((
        "fuse", tuple(sig), int(pad), int(split),
        tuple((a.shape, str(a.dtype)) for a in leaves),
    ))
    flat = exec_cache.dispatch(
        _fuse_jit(), (tuple(sig), int(pad), int(split)),
        _converge_leaves([np.asarray(starts, np.int32), *leaves]),
    )
    # flat is column-major: per column, `split` consecutive batch parts
    k = int(split)
    return [
        [flat[ci * k + j] for ci in range(len(specs))] for j in range(k)
    ]


def _jit_assemble(strct: tuple, padn: int, k: int, starts_arr, *arrs):
    import jax.numpy as jnp
    from jax import lax

    out = []
    i = 0  # leaf cursor
    pj = 0  # piece cursor (into starts_arr)
    for is_str, w, flags in strct:
        vs, ms, ls = [], [], []
        for hm, hl, size in flags:
            a0 = starts_arr[pj]
            pj += 1
            v = lax.dynamic_slice_in_dim(arrs[i], a0, size)
            i += 1
            if is_str and int(v.shape[1]) != w:
                v = jnp.pad(v, ((0, 0), (0, w - int(v.shape[1]))))
            vs.append(v)
            if hm:
                ms.append(lax.dynamic_slice_in_dim(arrs[i], a0, size))
                i += 1
            if hl:
                ls.append(lax.dynamic_slice_in_dim(arrs[i], a0, size))
                i += 1
        v = vs[0] if len(vs) == 1 else jnp.concatenate(vs)
        m = (
            (ms[0] if len(ms) == 1 else jnp.concatenate(ms))
            if ms else None
        )
        ln = (
            (ls[0] if len(ls) == 1 else jnp.concatenate(ls))
            if ls else None
        )
        if padn:
            v = jnp.concatenate(
                [v, jnp.zeros((padn,) + tuple(v.shape[1:]), v.dtype)]
            )
            if m is not None:
                m = jnp.concatenate([m, jnp.ones((padn,), bool)])
            if ln is not None:
                ln = jnp.concatenate([ln, jnp.zeros((padn,), ln.dtype)])
        if k == 1:
            out.append((v, m, ln))
        else:
            B = v.shape[0] // k
            for j in range(k):
                out.append((
                    lax.slice_in_dim(v, j * B, (j + 1) * B),
                    None if m is None
                    else lax.slice_in_dim(m, j * B, (j + 1) * B),
                    None if ln is None
                    else lax.slice_in_dim(ln, j * B, (j + 1) * B),
                ))
    return tuple(out)


def _fuse_jit():
    global _FUSE_JIT
    if _FUSE_JIT is None:
        import jax

        _FUSE_JIT = jax.jit(_jit_assemble, static_argnums=(0, 1, 2))
    return _FUSE_JIT


@dataclass
class LoaderBatch:
    """One fixed-shape training batch.

    ``columns`` are :class:`~parquet_floor_tpu.batch.columns.BatchColumn`
    in schema order (the positional contract of every other batch face)
    — NumPy arrays from the host face, device-resident ``jax.Array`` from
    the device face.  When the epoch's remainder was padded
    (``drop_remainder=False``), ``num_valid < batch_size`` and
    ``row_mask`` marks the real rows (True); padded slots are zeros and,
    for optional columns, null.
    """

    epoch: int
    index: int                   # batch index within the epoch
    columns: List[BatchColumn]
    num_valid: int
    row_mask: Optional[object] = None  # None when every row is real

    @property
    def batch_size(self) -> int:
        return int(self.columns[0].values.shape[0]) if self.columns else 0

    def column(self, name: str) -> BatchColumn:
        for c in self.columns:
            if ".".join(c.descriptor.path) == name or \
                    c.descriptor.path[0] == name:
                return c
        raise KeyError(f"no column named {name!r}")


def make_batch(specs: Sequence[ColumnSpec], parts: Sequence[Part],
               epoch: int, index: int, batch_size: int, valid: int,
               xp) -> LoaderBatch:
    """Assemble one batch, zero-padding (+ null-masking) the tail when a
    column still falls short of ``batch_size`` (the device face arrives
    pre-padded by :func:`fused_assemble`; the host face pads here)."""
    cols = []
    for spec, (v, m, ln) in zip(specs, parts):
        pad = batch_size - int(v.shape[0])
        if pad > 0:
            v = xp.concatenate(
                [v, xp.zeros((pad,) + tuple(v.shape[1:]), v.dtype)]
            )
            if m is not None:
                m = xp.concatenate([m, xp.ones((pad,), bool)])
            if ln is not None:
                ln = xp.concatenate([ln, xp.zeros((pad,), ln.dtype)])
        cols.append(BatchColumn(
            spec.descriptor, v, m, ln, f64_bits=spec.f64_bits,
        ))
    row_mask = (
        None if valid == batch_size else (xp.arange(batch_size) < valid)
    )
    return LoaderBatch(epoch, index, cols, valid, row_mask)
