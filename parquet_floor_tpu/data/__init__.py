"""Deterministic, checkpointable TPU input pipeline (``docs/data.md``).

The training-loop layer over the scan scheduler: ``DataLoader`` turns a
Parquet dataset into seeded-shuffled, epoch-aware, fixed-shape host or
device batches, sharded disjointly across hosts, with mid-epoch
checkpoint/resume that is bit-identical to an uninterrupted run.

* :mod:`~parquet_floor_tpu.data.order` — the pure order-plan math:
  contiguous unit shards, per-epoch unit permutations, the bounded
  block (window) shuffle, and the resume arithmetic.  All randomness is
  counter-based (Philox keyed on seed/epoch/block), so checkpoints carry
  seeds and cursors, never RNG state.
* :mod:`~parquet_floor_tpu.data.batcher` — carry-over re-slicing of
  ragged row groups into exact ``batch_size`` rows with static shapes
  (drop- or pad-remainder).
* :mod:`~parquet_floor_tpu.data.loader` — :class:`DataLoader` itself,
  driving ``scan.DatasetScanner(order=...)`` (host face) or the TPU
  engine's windowed ``iter_dataset_row_groups`` (device face).
"""

from .batcher import ColumnSpec, LoaderBatch, RowBuffer, make_batch
from .loader import DataLoader, DevicePrefetcher
from .order import EpochPlan, Unit, keyed_rng, shard_units

__all__ = [
    "ColumnSpec",
    "DataLoader",
    "DevicePrefetcher",
    "EpochPlan",
    "LoaderBatch",
    "RowBuffer",
    "Unit",
    "keyed_rng",
    "make_batch",
    "shard_units",
]
