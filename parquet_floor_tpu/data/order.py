"""Deterministic epoch order plans — the seeded, shardable, resumable
ordering layer of the training input pipeline (``docs/data.md``).

Everything here is pure metadata math over the unit list the loader read
from footers: no I/O, no mutable RNG.  Randomness is **counter-based**
(numpy ``Philox`` keyed by ``(seed, purpose, epoch, position)``), so
every draw is a pure function of its coordinates — the checkpoint never
has to serialize generator state, only seeds and cursors, and a resumed
stream replays the exact permutations of the uninterrupted one.

Three layers:

* **unit shard** — the global ``(file, row_group)`` unit list splits into
  contiguous per-host blocks (the ``parallel.multihost`` convention), so
  multihost loaders never overlap.  The shard, not the global list, is
  the shuffle domain: a host's stream depends only on (its shard's
  units, seed, epoch) — re-partitioning the fleet changes which units a
  host owns, but a host whose shard is unchanged replays the same
  stream.
* **unit permutation** — per epoch, the shard's units permute under a
  generator keyed on ``(seed, epoch)``.
* **window (block) shuffle** — each unit's rows chop into consecutive
  blocks of ``window`` rows and every block permutes, under a generator
  keyed on ``(seed, epoch, unit position)``.  Blocks never span units:
  the TPU engine then fuses each unit's whole-rows permutation into its
  decode executable (``out_perm``) — the shuffle rides the decode's own
  index arithmetic instead of paying a separate device pass — and the
  resume arithmetic needs only (unit index, row offset), never partial
  block state.  Cross-unit mixing comes from the unit permutation
  above; the window bounds how far rows move *within* a unit.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

_MASK64 = (1 << 64) - 1
# fixed odd multipliers (splitmix64 constants) keying the two streams
_STREAM_UNITS = 1
_STREAM_BLOCK = 2


class Unit(NamedTuple):
    """One schedulable decode unit: a row group of one dataset file."""

    file_index: int
    group_index: int
    num_rows: int


def keyed_rng(seed: int, stream: int, epoch: int,
              index: int = 0) -> np.random.Generator:
    """A counter-based generator for one (seed, stream, epoch, index)
    coordinate — same coordinates, same draws, on every run and host."""
    mix = (
        stream * 0x9E3779B97F4A7C15
        + epoch * 0xBF58476D1CE4E5B9
        + index * 0x94D049BB133111EB
    ) & _MASK64
    key = np.array([int(seed) & _MASK64, mix], dtype=np.uint64)
    return np.random.Generator(np.random.Philox(key=key))


def shard_units(units: Sequence[Unit], host_index: int,
                host_count: int) -> List[Unit]:
    """The contiguous block of ``units`` host ``host_index`` owns.

    Host ``p`` takes ``units[p*k : (p+1)*k]`` with ``k = ceil(n /
    host_count)`` — the same contiguous convention as
    ``parallel.multihost`` (block sharding preserves file locality, so a
    host's shuffled epoch touches only its own files).  Shards are
    disjoint and cover every unit; trailing hosts may own fewer (or
    zero) units when the counts don't divide.
    """
    if host_count < 1:
        raise ValueError(f"host_count must be >= 1, got {host_count}")
    if not 0 <= host_index < host_count:
        raise ValueError(
            f"host_index {host_index} outside [0, {host_count})"
        )
    k = -(-len(units) // host_count) if units else 0
    return list(units[host_index * k : (host_index + 1) * k])


class EpochPlan:
    """The fully-determined order of one (epoch, shard): permuted units,
    row prefix sums, per-unit window permutations, and the resume
    arithmetic."""

    def __init__(self, units: Sequence[Unit], seed: Optional[int],
                 epoch: int, window: int = 0):
        if window < 0:
            raise ValueError(f"window must be >= 0, got {window}")
        if window > 1 and seed is None:
            raise ValueError(
                "a shuffle window needs a shuffle_seed (the window "
                "permutations are keyed on it)"
            )
        self.seed = seed
        self.epoch = int(epoch)
        # window <= 1 is identity: no row ever moves
        self.window = int(window) if window > 1 else 0
        units = list(units)
        if seed is not None and len(units) > 1:
            perm = keyed_rng(seed, _STREAM_UNITS, epoch).permutation(
                len(units)
            )
            units = [units[int(i)] for i in perm]
        self.units: List[Unit] = units
        starts = np.zeros(len(units) + 1, dtype=np.int64)
        np.cumsum([u.num_rows for u in units], out=starts[1:])
        self._starts = starts
        self.total_rows = int(starts[-1])

    # -- batch / unit arithmetic --------------------------------------------

    def n_batches(self, batch_size: int, drop_remainder: bool) -> int:
        if drop_remainder:
            return self.total_rows // batch_size
        return -(-self.total_rows // batch_size)

    def unit_perm(self, pos: int) -> Optional[np.ndarray]:
        """The whole-rows output permutation of the unit at (permuted)
        position ``pos`` — a pure function of (seed, epoch, pos) and the
        unit's row count, or ``None`` when no window shuffle is active.

        Rows chop into consecutive ``window``-row blocks (the tail block
        may be short) and each block permutes independently; the
        concatenation is one int32 permutation the TPU engine fuses into
        the unit's decode (``out_perm``)."""
        if not self.window:
            return None
        n = self.units[pos].num_rows
        rng = keyed_rng(self.seed, _STREAM_BLOCK, self.epoch, pos)
        parts = [
            off + rng.permutation(min(self.window, n - off))
            for off in range(0, n, self.window)
        ]
        if not parts:
            return np.zeros(0, dtype=np.int32)
        return np.concatenate(parts).astype(np.int32, copy=False)

    def locate_row(self, row: int) -> Tuple[int, int]:
        """(unit index, row offset within it) of output-stream position
        ``row`` — zero-row units are skipped by construction."""
        if not 0 <= row < self.total_rows:
            raise ValueError(
                f"row {row} outside epoch of {self.total_rows} rows"
            )
        i = int(np.searchsorted(self._starts, row, side="right")) - 1
        return i, row - int(self._starts[i])

    def resume_point(self, batches_done: int, batch_size: int
                     ) -> Tuple[int, int]:
        """Where to restart so that batch ``batches_done`` is the next
        one emitted: ``(unit_index, rows_to_drop)`` — decode restarts at
        ``unit_index`` (whose permutation re-derives exactly — it is a
        pure function of its position) and the first ``rows_to_drop``
        rows of its permuted output were already emitted before the
        checkpoint.  Because blocks never span units, no partial block
        state exists to reconstruct."""
        skip = batches_done * batch_size
        if skip == 0:
            return 0, 0
        return self.locate_row(skip)
