"""``DataLoader`` — the deterministic, checkpointable training input
pipeline over the scan scheduler (``docs/data.md``).

What a training loop consumes is not "a fast reader": it is a stream of
seeded-shuffled, epoch-aware, fixed-shape batches that can be
checkpointed mid-epoch and resumed bit-identically.  This module is that
layer, built from pieces the repo already has:

* the **order plan** (``data.order``): contiguous host shards of the
  ``(file, row_group)`` unit list, per-epoch unit permutations, and the
  bounded block (window) shuffle — all counter-based, so the checkpoint
  is seeds + cursors, never RNG state;
* the **scan scheduler**: the host face drives
  ``scan.DatasetScanner(order=...)`` (coalesced vectored reads, bounded
  prefetch, permuted delivery); the device face drives the TPU engine's
  windowed ``iter_dataset_row_groups`` (files open DEPTH-ahead of the
  shuffled order and close after their last scheduled group);
* the **batcher** (``data.batcher``): carry-over re-slicing of ragged
  row groups into exact ``batch_size`` rows with static shapes.

Observability: the loader emits ``data.*`` counters/spans (registered in
``trace.names``) into the tracer scope active at construction, and
builds a per-epoch :class:`~parquet_floor_tpu.utils.trace.ScanReport`
from snapshot deltas — ``loader.report()`` merges them via
``ScanReport.merge``.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import replace
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..errors import UnsupportedFeatureError
from ..format.file_read import ParquetFileReader, ReaderOptions, SalvageReport
from ..format.parquet_thrift import Type
from ..format.schema import dataset_schema_key
from ..scan.plan import ScanOptions
from ..utils import trace
from .batcher import (
    ColumnSpec,
    LoaderBatch,
    RowBuffer,
    aligned_split,
    fused_assemble,
    grow_widths,
    make_batch,
    permute_parts,
)
from .order import EpochPlan, Unit, shard_units

_STATE_VERSION = 1
# the fingerprint: state from one loader configuration must not restore
# into another (a silently different stream would defeat the checkpoint)
_FP_FIELDS = (
    "batch_size", "shuffle_seed", "shuffle_window", "drop_remainder",
    "num_epochs", "shard", "engine", "units", "rows", "columns",
)


def _resolve_source(src):
    """A source entry may be path-like, an open positional source, or a
    zero-arg FACTORY returning one (the shape fault-injection tests and
    exotic storage want — a factory gives every open a fresh object, so
    multi-epoch loaders never reuse a closed source)."""
    if callable(src) and not hasattr(src, "read_at"):
        return src()
    return src


def _delta_counters(before: Dict[str, int], after: Dict[str, int]
                    ) -> Dict[str, int]:
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def _delta_stats(before: Dict[str, dict], after: Dict[str, dict]
                 ) -> Dict[str, dict]:
    out = {}
    for k, st in after.items():
        b = before.get(k, {})
        dc = st["count"] - b.get("count", 0)
        ds = st["seconds"] - b.get("seconds", 0.0)
        db = st["bytes"] - b.get("bytes", 0)
        dss = (
            st.get("self_seconds", st["seconds"])
            - b.get("self_seconds", b.get("seconds", 0.0))
        )
        if dc or ds or db:
            out[k] = {
                "count": dc,
                "seconds": round(ds, 6),
                "bytes": db,
                "MB_per_s": round(db / ds / 1e6, 1) if ds > 0 else 0.0,
                "self_seconds": round(dss, 6),
            }
    return out


class DevicePrefetcher:
    """Double-buffered iteration over a :class:`DataLoader` —
    ``loader.prefetch_to_device(n)`` (docs/perf.md).

    Keeps up to ``depth`` batches IN FLIGHT ahead of the consumer: each
    pull advances the loader (which advances the decode pipeline — on
    the device face that means the engine's stage worker reads and the
    ship worker transfers batch k+1's arena/slab while the consumer's
    step k computes) and ships every batch leaf with one asynchronous
    ``jax.device_put``, so by the time the training step asks for batch
    k+1 its arrays are already resident (or their H2D is already in
    flight) instead of starting the transfer on the critical path.
    Device-face batches are already device-resident ``jax.Array``\\ s —
    for them the put is a no-op and the win is the pipeline advance;
    host-face batches pay their H2D here, off the step's critical path.

    Checkpointing stays EXACT: the prefetcher snapshots
    ``loader.state()`` right after each pull, and :meth:`state` returns
    the snapshot of the last batch the CONSUMER received — restoring it
    replays every batch the consumer has not seen, including the ones
    that were sitting in the prefetch buffer.  (Calling
    ``loader.state()`` directly while a prefetcher is active reflects
    the pulled-ahead position instead — use the prefetcher's.)
    """

    def __init__(self, loader: "DataLoader", depth: int = 2, device=None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._loader = loader
        self._depth = int(depth)
        self._device = device
        self._buf: deque = deque()      # (shipped batch, state snapshot)
        self._last_state = loader.state()
        self._done = False

    def __iter__(self):
        return self

    def _ship(self, batch: LoaderBatch) -> LoaderBatch:
        import jax

        tracer = self._loader._tracer
        with tracer.span("data.prefetch_to_device"):
            leaves = []
            spec = []
            for c in batch.columns:
                for a in (c.values, c.mask, c.lengths):
                    spec.append(a is not None)
                    if a is not None:
                        leaves.append(a)
            has_rm = batch.row_mask is not None
            if has_rm:
                leaves.append(batch.row_mask)
            if self._device is None and all(
                isinstance(a, jax.Array) for a in leaves
            ):
                # device-face batch already resident on the target: the
                # put would be a no-op — the prefetch win here is the
                # PULL itself (the decode pipeline advanced a batch
                # ahead), so skip the dispatch round trip per leaf
                return batch
            # ONE asynchronous transfer for the whole batch: arrays come
            # back as futures, the H2D overlaps the consumer's step
            shipped = jax.device_put(leaves, self._device)
        it = iter(shipped)
        flags = iter(spec)
        cols = []
        for c in batch.columns:
            v, m, ln = (
                (next(it) if next(flags) else None) for _ in range(3)
            )
            cols.append(replace(c, values=v, mask=m, lengths=ln))
        return LoaderBatch(
            batch.epoch, batch.index, cols, batch.num_valid,
            next(it) if has_rm else None,
        )

    def _pull(self) -> bool:
        if self._done:
            return False
        try:
            nxt = next(self._loader)
        except StopIteration:
            self._done = True
            return False
        tracer = self._loader._tracer
        self._buf.append((self._ship(nxt), self._loader.state()))
        tracer.count("data.prefetch_to_device_batches")
        tracer.gauge_max(
            "data.prefetch_to_device_depth_max", len(self._buf)
        )
        return True

    def __next__(self) -> LoaderBatch:
        while len(self._buf) < self._depth and self._pull():
            pass
        if not self._buf:
            raise StopIteration
        batch, snap = self._buf.popleft()
        self._last_state = snap
        return batch

    def state(self) -> dict:
        """The loader state as of the last batch the consumer RECEIVED
        (buffered batches count as not-yet-emitted) — hand it to
        ``DataLoader.restore`` exactly like ``loader.state()``."""
        return self._last_state

    def close(self) -> None:
        """Drop the buffered batches (they were already pulled; the
        loader itself stays open — close it separately)."""
        self._buf.clear()
        self._done = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DataLoader:
    """Seeded, sharded, checkpointable batch stream over a Parquet
    dataset.

    ``DataLoader(sources, batch_size, shuffle_seed=7, num_epochs=2,
    drop_remainder=True, shard=(host_index, host_count),
    options=ScanOptions(...))`` yields
    :class:`~parquet_floor_tpu.data.batcher.LoaderBatch` — fixed-shape
    host batches (``engine="host"``, NumPy) or device batches
    (``engine="tpu"``, ``jax.Array``) — deterministically: same
    configuration + same seed ⇒ the same batch stream, on every run.

    * ``shuffle_seed=None`` streams units in (file, row-group) order —
      the unshuffled reference stream.  With a seed, each epoch permutes
      the shard's units (keyed on ``(seed, epoch)``); ``shuffle_window=W``
      additionally mixes rows within consecutive W-row blocks of the
      stream (bounded memory: at most ~W + batch_size rows buffer).
    * ``shard=(host_index, host_count)`` takes the host's contiguous
      block of the unit list (disjoint across hosts — the
      ``parallel.multihost.host_shard()`` contract).  A host's stream
      depends only on its shard + seed + epoch, never on the fleet size.
    * ``state()``/``restore(state)`` checkpoint between batches: epoch,
      batch cursor, and the string-width high-water marks — a small
      JSON-serializable dict.  The RNG is counter-based, so no generator
      state rides the checkpoint; resume is bit-identical to the
      uninterrupted run.
    * ``options`` is the scan scheduler's
      :class:`~parquet_floor_tpu.scan.ScanOptions` (host face: coalesced
      reads, prefetch budget, threads).  ``reader_options`` is the usual
      :class:`~parquet_floor_tpu.ReaderOptions` (``io_retries`` for
      flaky storage; ``verify_crc`` alone pins the host face).  With
      ``salvage=True`` the loader keeps flowing over corrupt units:
      page-null damage passes through as masked nulls, units with
      GEOMETRY-changing damage (chunk quarantine, row-mask drops) are
      dropped whole, recorded in ``state()`` (resume stays
      bit-identical), counted as ``data.units_quarantined``, and folded
      into :attr:`salvage_report` (docs/robustness.md).

    Repeated (nested) columns are not batchable into fixed shapes and
    raise at construction; project them away with ``columns=``.
    """

    def __init__(self, sources: Sequence, batch_size: int, *,
                 columns: Optional[Sequence[str]] = None,
                 shuffle_seed: Optional[int] = None,
                 shuffle_window: int = 0,
                 num_epochs: Optional[int] = 1,
                 drop_remainder: bool = True,
                 shard: Optional[tuple] = None,
                 engine: str = "host",
                 options: Optional[ScanOptions] = None,
                 reader_options: Optional[ReaderOptions] = None,
                 float64_policy: str = "bits"):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if engine not in ("host", "tpu"):
            raise ValueError(f"bad engine {engine!r}: expected host|tpu")
        if num_epochs is not None and num_epochs < 1:
            raise ValueError(
                f"num_epochs must be >= 1 or None (endless), got {num_epochs}"
            )
        if shuffle_window < 0:
            raise ValueError(
                f"shuffle_window must be >= 0, got {shuffle_window}"
            )
        if shuffle_window > 1 and shuffle_seed is None:
            raise ValueError(
                "shuffle_window needs shuffle_seed (window permutations "
                "are keyed on it)"
            )
        if engine == "tpu" and reader_options is not None and \
                reader_options.verify_crc and not reader_options.salvage:
            # with salvage=True the device face delegates every unit's
            # decode to the host salvage engine, which DOES run the CRC
            # check — the combination is honored (TpuRowGroupReader's
            # contract); verify_crc alone still pins the host face
            raise UnsupportedFeatureError(
                "ReaderOptions.verify_crc is a host-engine feature; use "
                'engine="host" for CRC-checked loading'
            )
        self._sources = list(sources)
        if not self._sources:
            raise ValueError("DataLoader needs at least one source")
        self._batch_size = int(batch_size)
        self._seed = shuffle_seed
        self._window = int(shuffle_window) if shuffle_window > 1 else 0
        self._num_epochs = num_epochs
        self._drop_remainder = bool(drop_remainder)
        self._shard = (0, 1) if shard is None else (int(shard[0]), int(shard[1]))
        self._engine = engine
        self._scan = options or ScanOptions()
        self._reader_options = reader_options
        self._f64 = float64_policy
        # the loader is ATTRIBUTED to the tracer scope active here, like
        # DatasetScanner: all data.* metrics and the per-epoch reports
        # land on it no matter which scope later drives iteration
        self._tracer = trace.current()

        self._units, self._selected = self._scan_footers(columns)
        self._check_batchable()
        self._shard_units = shard_units(self._units, *self._shard)
        self._shard_rows = sum(u.num_rows for u in self._shard_units)
        if self._drop_remainder:
            self._n_batches = self._shard_rows // self._batch_size
        else:
            self._n_batches = -(-self._shard_rows // self._batch_size)

        self._specs = [
            ColumnSpec(
                name=".".join(d.path) if len(d.path) > 1 else d.path[0],
                descriptor=d,
                is_string=d.physical_type == Type.BYTE_ARRAY,
                has_mask=d.max_definition_level > 0,
                f64_bits=(
                    engine == "tpu"
                    and d.physical_type == Type.DOUBLE
                    and float64_policy == "bits"
                ),
            )
            for d in self._selected
        ]
        self._widths: Dict[str, int] = {}  # string-width HWMs (checkpointed)
        # salvage (docs/robustness.md): units whose decode recorded
        # GEOMETRY-changing damage — a chunk quarantined or rows dropped
        # by the row-mask tier — are quarantined WHOLE at this layer
        # (fixed-shape batches cannot absorb a missing column or a
        # shifted row count) and recorded in checkpoint state, so resume
        # arithmetic replays the identical stream.  Page-null damage
        # keeps geometry and flows through as masked nulls.
        self._salvage = (
            reader_options is not None and reader_options.salvage
        )
        self._quarantined: set = set()       # {(file_index, group_index)}
        self._salvage_seen: set = set()      # units folded into the report
        self._salvage_report = SalvageReport() if self._salvage else None
        self._epoch = 0
        self._batch_in_epoch = 0
        self._gen = None
        self._closed = False
        self._epoch_reports: List[trace.ScanReport] = []
        self._c0: Dict[str, int] = {}
        self._s0: Dict[str, dict] = {}
        self._gw: Optional[trace.GaugeWindow] = None
        self._hw: Optional[trace.HistogramWindow] = None
        self._t_epoch: Optional[float] = None

    # -- construction-time metadata scan ------------------------------------

    def _scan_footers(self, columns):
        """One footer-only pass over every source: the unit list (row
        counts included — the resume arithmetic needs them), the selected
        descriptors, the dataset schema check, and the parsed
        ``ParquetMetadata`` per file (``self._meta`` — every later open,
        on either face and in every epoch, reuses it instead of
        re-parsing the footer), all before the first batch.  Sources
        open fresh and close again (paths and factories re-open cheaply;
        an already-open source object is consumed by this pass — pass a
        factory if you need multi-open semantics)."""
        want = set(columns) if columns else None
        units: List[Unit] = []
        selected = None
        first_key = None
        self._meta = []
        for fi, src in enumerate(self._sources):
            with ParquetFileReader(
                _resolve_source(src), options=self._reader_options
            ) as r:
                key = dataset_schema_key(r.schema.columns)
                if first_key is None:
                    first_key = key
                    selected = [
                        c for c in r.schema.columns
                        if want is None or c.path[0] in want
                    ]
                    if not selected:
                        raise ValueError(
                            f"columns={sorted(want)} selects nothing"
                        )
                elif key != first_key:
                    raise ValueError(
                        f"dataset file {fi} disagrees with the first "
                        "file's schema"
                    )
                self._meta.append(r.metadata)
                for gi, rg in enumerate(r.row_groups):
                    units.append(Unit(fi, gi, int(rg.num_rows or 0)))
        return units, selected

    def _check_batchable(self):
        repeated = [
            ".".join(d.path) for d in self._selected
            if d.max_repetition_level > 0
        ]
        if repeated:
            raise UnsupportedFeatureError(
                f"repeated columns {repeated} cannot batch into fixed "
                "shapes; project them away with columns=..."
            )

    # -- salvage: unit-level quarantine --------------------------------------

    def _effective_shard_units(self):
        """The shard's units with quarantined ones at ZERO rows — the
        list every epoch plan and all resume arithmetic runs on, so a
        quarantined unit before the resume point shifts nothing."""
        if not self._quarantined:
            return self._shard_units
        return [
            u._replace(num_rows=0)
            if (u.file_index, u.group_index) in self._quarantined else u
            for u in self._shard_units
        ]

    def _effective_counts(self):
        """(rows, batches) of one epoch under the CURRENT quarantine
        set."""
        rows = sum(u.num_rows for u in self._effective_shard_units())
        if self._drop_remainder:
            return rows, rows // self._batch_size
        return rows, -(-rows // self._batch_size)

    def _unit_geometry_damaged(self, rep, group_index) -> bool:
        return rep is not None and rep.geometry_damaged(group_index)

    def _fold_unit_report(self, key, rep) -> None:
        """Fold one unit's report into the loader's (once per unit, in
        first-delivery order — re-decodes across epochs must not double
        the books)."""
        if rep is None or key in self._salvage_seen:
            return
        self._salvage_seen.add(key)
        self._salvage_report.merge_in(rep)

    def _record_quarantine(self, unit, rep) -> None:
        """A unit came back geometry-damaged: drop it WHOLE, remember it
        (state() carries the set, so resume replays the same stream) and
        account the loss."""
        key = (unit.file_index, unit.group_index)
        self._fold_unit_report(key, rep)
        if key in self._quarantined:
            return
        self._quarantined.add(key)
        self._tracer.count("data.units_quarantined")
        self._tracer.decision("data.unit_quarantined", {
            "file": unit.file_index,
            "row_group": unit.group_index,
            "rows": unit.num_rows,
        })

    # -- iteration ----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> LoaderBatch:
        with trace.using(self._tracer):
            return self._next_batch()

    def _next_batch(self) -> LoaderBatch:
        if self._closed:
            raise StopIteration
        while True:
            # an empty shard — or a shard salvage quarantined down to
            # zero surviving rows — is a valid no-op loader, including
            # under num_epochs=None (it must stop, not spin)
            if self._n_batches == 0:
                raise StopIteration
            if self._num_epochs is not None and \
                    self._epoch >= self._num_epochs:
                raise StopIteration
            if self._batch_in_epoch >= self._n_batches:
                if self._gen is not None:
                    # the epoch's generator just emitted its last batch:
                    # close it out (records the epoch report, advances
                    # the epoch, resets the batch cursor)
                    self._finish_epoch()
                else:
                    # restored exactly at an epoch end: no stream ran
                    # here, so there is no report to record
                    self._epoch += 1
                    self._batch_in_epoch = 0
                continue
            if self._gen is None:
                self._start_epoch()
            with self._tracer.span("data.next_batch",
                                   observe="data.next_batch_seconds"):
                try:
                    batch = next(self._gen)
                except StopIteration:
                    self._finish_epoch()
                    continue
            self._batch_in_epoch += 1
            self._tracer.count("data.batches_emitted")
            self._tracer.count("data.rows_emitted", batch.num_valid)
            if batch.num_valid < self._batch_size:
                self._tracer.count(
                    "data.rows_padded", self._batch_size - batch.num_valid
                )
            return batch

    def _start_epoch(self):
        # plans run on the EFFECTIVE unit list (quarantined units at 0
        # rows): the unit permutation is independent of row counts and
        # the window perms are keyed per position, so zeroing a unit
        # perturbs nothing else — resume arithmetic just skips it
        plan = EpochPlan(
            self._effective_shard_units(), self._seed, self._epoch,
            self._window,
        )
        if self._salvage:
            _, self._n_batches = self._effective_counts()
        self._c0 = self._tracer.counters()
        self._s0 = self._tracer.stats()
        if self._gw is not None:       # restore() mid-epoch: stale window
            self._gw.close()
        if self._hw is not None:
            self._hw.close()
        self._gw = self._tracer.gauge_window()
        # latency distributions delta the same way gauges do: per-epoch
        # windows observe the writes directly (docs/observability.md)
        self._hw = self._tracer.histogram_window()
        self._t_epoch = time.perf_counter()
        u0, _off = plan.resume_point(
            self._batch_in_epoch, self._batch_size
        )
        self._tracer.decision("data.epoch_plan", {
            "epoch": self._epoch,
            "units": len(plan.units),
            "rows": plan.total_rows,
            "seed": self._seed,
            "window": self._window,
            "start_batch": self._batch_in_epoch,
        })
        self._tracer.count("data.units_scheduled", len(plan.units) - u0)
        self._gen = self._epoch_batches(plan, self._epoch,
                                        self._batch_in_epoch)

    def _finish_epoch(self):
        if self._gen is not None:
            # the epoch generator may still be SUSPENDED at its last
            # yield (the consumer stops pulling once n_batches arrived):
            # close it explicitly so the scan/engine stream's finally
            # runs NOW (workers drain, files close), not at GC time
            self._gen.close()
            self._gen = None
        # effective counts: a quarantine discovered mid-epoch shrank the
        # stream below the epoch-start plan — the books must reflect
        # what actually flowed (and the NEXT epoch's n_batches with it)
        rows_eff, n_eff = self._effective_counts()
        if self._salvage:
            self._n_batches = n_eff
        if self._drop_remainder:
            # the remainder policy's loss, accounted centrally: the
            # generator's own tail never runs in the normal case (it
            # stays suspended at the last batch's yield), so the count
            # cannot live there
            tail = rows_eff - n_eff * self._batch_size
            if tail:
                self._tracer.count("data.rows_dropped", tail)
        wall = (
            time.perf_counter() - self._t_epoch
            if self._t_epoch is not None else None
        )
        self._t_epoch = None
        budget = self._scan.prefetch_bytes if self._engine == "host" else None
        # gauges come from the epoch's window, not the cumulative tracer
        # snapshot: a cumulative max cannot be delta'd, so epoch N must
        # not inherit epoch N-1's high-water marks
        gauges = self._gw.close() if self._gw is not None else {}
        self._gw = None
        hists = self._hw.close() if self._hw is not None else {}
        self._hw = None
        self._epoch_reports.append(trace.scan_report_from(
            _delta_stats(self._s0, self._tracer.stats()),
            _delta_counters(self._c0, self._tracer.counters()),
            gauges,
            wall_seconds=wall, budget_bytes=budget,
            histograms={k: h.as_dict() for k, h in hists.items()},
        ))
        self._tracer.count("data.epochs_completed")
        self._epoch += 1
        self._batch_in_epoch = 0

    # -- the per-epoch pipeline ---------------------------------------------

    def _epoch_batches(self, plan: EpochPlan, epoch: int, start_batch: int):
        """Generator of this epoch's remaining batches: window-shuffled
        source groups (the permutation fused into each group's decode —
        device face — or applied eagerly per group — host face) →
        carry-over batcher → remainder policy.  ``start_batch > 0`` is
        the resume path: decode restarts at the interrupted unit and the
        already-emitted head of its (re-derived) permuted output drops
        before batching."""
        B = self._batch_size
        n_batches = plan.n_batches(B, self._drop_remainder)
        if start_batch >= n_batches:
            return
        unit0, off0 = plan.resume_point(start_batch, B)
        xp = self._xp()
        fused = self._engine == "tpu"
        batchbuf = RowBuffer(self._specs, xp, self._widths)
        emitted = start_batch

        def emit_ready():
            """Every complete batch the buffer holds — in ONE compiled
            dispatch on the device face, eager NumPy takes on host."""
            nonlocal emitted
            k = min(batchbuf.rows // B, n_batches - emitted)
            if k <= 0:
                return
            if fused:
                for parts in fused_assemble(
                    self._specs, batchbuf.take_windows(k * B),
                    batchbuf.widths, split=k,
                ):
                    yield make_batch(
                        self._specs, parts, epoch, emitted, B, B, xp
                    )
                    emitted += 1
            else:
                for _ in range(k):
                    yield make_batch(
                        self._specs, batchbuf.take(B), epoch, emitted,
                        B, B, xp,
                    )
                    emitted += 1

        stream = (
            self._host_groups(plan, unit0)
            if self._engine == "host"
            else self._device_groups(plan, unit0)
        )
        try:
            first = True
            for n_rows, parts in stream:
                skip = off0 if first else 0
                first = False
                if (fused and batchbuf.rows == 0 and n_rows
                        and n_rows % B == 0 and skip % B == 0):
                    # GROUP-ALIGNED fast path: no carry pending and the
                    # group cuts into whole batches — one static-slice
                    # dispatch, no traced offsets, no concatenation
                    # (docs/data.md: pick batch_size to divide the
                    # writer's row-group size and stay on this path)
                    grow_widths(self._specs, parts, self._widths)
                    k = n_rows // B
                    drop = skip // B  # resume: already-emitted head
                    take = min(k - drop, n_batches - emitted)
                    if take > 0:
                        batches = aligned_split(
                            self._specs, parts, self._widths, k
                        )
                        for j in range(drop, drop + take):
                            yield make_batch(
                                self._specs, batches[j], epoch, emitted,
                                B, B, xp,
                            )
                            emitted += 1
                    continue
                batchbuf.push(parts, n_rows, skip)
                yield from emit_ready()
                self._tracer.gauge_max("data.carry_rows_max", batchbuf.rows)
            # pad-remainder tail (drop-remainder's loss is accounted in
            # _finish_epoch: this generator stays suspended at the last
            # full batch's yield and never reaches here in that mode)
            r = batchbuf.rows
            if r and emitted < n_batches and not self._drop_remainder:
                parts = fused_assemble(
                    self._specs, batchbuf.take_windows(r),
                    batchbuf.widths, pad=B - r,
                )[0] if fused else batchbuf.take(r)
                yield make_batch(
                    self._specs, parts, epoch, emitted, B, r, xp
                )
        finally:
            stream.close()

    def _xp(self):
        if self._engine == "host":
            return np
        import jax.numpy as jnp

        return jnp

    # -- the two decode faces -----------------------------------------------

    def _host_groups(self, plan: EpochPlan, unit0: int):
        """Group-permuted host decode through the scan scheduler
        (``DatasetScanner(order=...)``, footers reused from
        construction): coalesced vectored reads and bounded cross-file
        prefetch run ahead of the batcher; each group's window
        permutation applies eagerly (NumPy fancy-indexing) as it
        arrives."""
        from ..api.reader import _host_batch_columns
        from ..scan.executor import DatasetScanner

        sched = self._schedule(plan, unit0)
        scanner = DatasetScanner(
            self._sources,
            columns=[d.path[0] for d in self._selected],
            options=self._reader_options, scan=self._scan,
            order=[(u.file_index, u.group_index) for _, u in sched],
            metadata=self._meta,
        )
        try:
            for (pos, u), unit in zip(sched, scanner):
                if self._salvage:
                    key = (u.file_index, u.group_index)
                    if self._unit_geometry_damaged(
                        unit.salvage, unit.group_index
                    ):
                        self._record_quarantine(u, unit.salvage)
                        continue
                    self._fold_unit_report(key, unit.salvage)
                cols = _host_batch_columns(
                    self._selected, unit.batch, unit.group_index
                )
                parts = [self._host_part(c) for c in cols]
                perm = plan.unit_perm(pos)
                if perm is not None:
                    parts = permute_parts(parts, perm)
                yield unit.batch.num_rows, parts
        finally:
            scanner.close()

    def _schedule(self, plan: EpochPlan, unit0: int):
        """The epoch's decode schedule from ``unit0`` on: (plan
        position, unit) pairs, with KNOWN-quarantined units excluded —
        they contribute zero rows, so decoding them again would only
        re-trip their decode errors (the quarantine map's argument, at
        the unit level)."""
        return [
            (unit0 + j, u)
            for j, u in enumerate(plan.units[unit0:])
            if not (
                self._salvage
                and (u.file_index, u.group_index) in self._quarantined
            )
        ]

    @staticmethod
    def _host_part(bc):
        """One host BatchColumn → the batcher's (values, mask, lengths)
        triple; strings become padded byte rows (group-local width — the
        buffer's HWM pads further)."""
        from ..format.encodings.plain import ByteArrayColumn

        if isinstance(bc.values, ByteArrayColumn):
            return (
                bc.values.padded_matrix(),
                bc.mask,
                np.asarray(bc.lengths, dtype=np.int64),
            )
        return np.asarray(bc.values), bc.mask, None

    def _device_groups(self, plan: EpochPlan, unit0: int):
        """Group-permuted device decode through the engine's WINDOWED
        dataset pipeline: readers open lazily DEPTH-ahead of the
        shuffled order (reusing the footers parsed at construction) and
        close right after their last scheduled group, so fd usage
        follows the order's locality, not the dataset size.  Each unit's
        window permutation rides its decode executable (``out_perm``) —
        the shuffle costs index arithmetic the decode already pays for,
        not a separate device pass."""
        from ..format.file_read import ParquetFileReader
        from ..tpu.engine import TpuRowGroupReader, iter_dataset_row_groups

        sched = self._schedule(plan, unit0)
        last = {}
        for k, (_, u) in enumerate(sched):
            last[u.file_index] = k
        opened: dict = {}

        def opener(fi):
            def open_():
                r = opened.get(fi)
                if r is None:
                    r = opened[fi] = TpuRowGroupReader(
                        ParquetFileReader(
                            _resolve_source(self._sources[fi]),
                            options=self._reader_options,
                            metadata=self._meta[fi],
                        ),
                        float64_policy=self._f64, dict_form="gather",
                    )
                return r
            return open_

        def tasks():
            for k, (pos, u) in enumerate(sched):
                yield (
                    opener(u.file_index), u.group_index,
                    k == last[u.file_index],
                    plan.unit_perm(pos),
                )

        gen = iter_dataset_row_groups(
            tasks(), columns=[d.path[0] for d in self._selected]
        )
        try:
            for (pos, u), cols in zip(sched, gen):
                if self._salvage:
                    # the engine stashed this unit's report before
                    # yielding it (its reader may retire right after)
                    tpu = opened.get(u.file_index)
                    rep = (
                        tpu.take_unit_report(u.group_index)
                        if tpu is not None else None
                    )
                    key = (u.file_index, u.group_index)
                    if self._unit_geometry_damaged(rep, u.group_index):
                        self._record_quarantine(u, rep)
                        continue
                    self._fold_unit_report(key, rep)
                parts = []
                for spec in self._specs:
                    dc = cols.get(spec.name)
                    if dc is None:
                        raise ValueError(
                            f"row group {u.group_index} missing column "
                            f"{spec.name}"
                        )
                    parts.append((dc.values, dc.mask, dc.lengths))
                yield u.num_rows, parts
        finally:
            gen.close()

    # -- checkpoint / restore ------------------------------------------------

    def _fingerprint(self) -> dict:
        return {
            "batch_size": self._batch_size,
            "shuffle_seed": self._seed,
            "shuffle_window": self._window,
            "drop_remainder": self._drop_remainder,
            "num_epochs": self._num_epochs,
            "shard": list(self._shard),
            "engine": self._engine,
            "units": len(self._units),
            "rows": self._shard_rows,
            "columns": [s.name for s in self._specs],
        }

    def state(self) -> dict:
        """The loader's position as a small JSON-serializable dict —
        valid between batches.  Captures epoch, the next batch index,
        and the string-width HWMs (batch shapes must replay), plus the
        configuration fingerprint :meth:`restore` validates.  Seeds and
        cursors fully determine the remaining stream (the RNG is
        counter-based), so no generator state is stored."""
        return {
            "version": _STATE_VERSION,
            "epoch": self._epoch,
            "batch": self._batch_in_epoch,
            "str_widths": dict(self._widths),
            # salvage: quarantined units ride the checkpoint, so resume
            # arithmetic replays the identical (shrunken) stream without
            # re-decoding the damage — bit-identical resume holds with a
            # quarantined unit before OR after the resume point
            "quarantined": sorted(
                [int(f), int(g)] for f, g in self._quarantined
            ),
            **self._fingerprint(),
        }

    def restore(self, state: dict) -> "DataLoader":
        """Position this loader at a previously saved :meth:`state`.

        The loader must be configured identically to the one that saved
        the state (checked against the embedded fingerprint); the
        remaining batch stream is then bit-identical to the
        uninterrupted run's.  Restoring mid-iteration abandons the
        current epoch stream first.  Returns ``self``::

            loader = DataLoader(paths, 256, shuffle_seed=7).restore(ckpt)
        """
        if state.get("version") != _STATE_VERSION:
            raise ValueError(
                f"unknown loader state version {state.get('version')!r}"
            )
        fp = self._fingerprint()
        bad = {
            k: (state.get(k), fp[k]) for k in _FP_FIELDS
            if state.get(k) != fp[k]
        }
        if bad:
            raise ValueError(
                "loader state does not match this configuration: "
                + ", ".join(
                    f"{k}: saved {s!r} vs here {h!r}"
                    for k, (s, h) in sorted(bad.items())
                )
            )
        quarantined = {
            (int(f), int(g)) for f, g in (state.get("quarantined") or [])
        }
        if quarantined and not self._salvage:
            raise ValueError(
                "state records quarantined units but this loader has "
                "salvage off — restoring it would silently change the "
                "stream; configure ReaderOptions(salvage=True)"
            )
        known = {(u.file_index, u.group_index) for u in self._units}
        bad_units = quarantined - known
        if bad_units:
            raise ValueError(
                f"state quarantines unknown units {sorted(bad_units)}"
            )
        self._quarantined = quarantined
        if self._salvage:
            # the batch-bound check below must run against the batch
            # count the RESTORED quarantine set implies
            _, self._n_batches = self._effective_counts()
        epoch, batch = int(state["epoch"]), int(state["batch"])
        if batch < 0 or (self._n_batches and batch > self._n_batches):
            raise ValueError(
                f"state batch {batch} outside epoch of "
                f"{self._n_batches} batches"
            )
        if self._gen is not None:
            self._gen.close()
            self._gen = None
        if self._gw is not None:       # abandoned epoch's windows
            self._gw.close()
            self._gw = None
        if self._hw is not None:
            self._hw.close()
            self._hw = None
        self._epoch = epoch
        self._batch_in_epoch = batch
        self._widths = {
            str(k): int(v) for k, v in (state.get("str_widths") or {}).items()
        }
        self._tracer.decision("data.resume", {"epoch": epoch, "batch": batch})
        return self

    # -- device double-buffering ----------------------------------------------

    def prefetch_to_device(self, depth: int = 2, device=None
                           ) -> DevicePrefetcher:
        """Iterate this loader with up to ``depth`` batches in flight
        ahead of the consumer (docs/perf.md): batch k+1's decode
        pipeline advance and its H2D transfer run under step k's
        compute, so the training step stops paying transfer latency on
        its critical path.  ``depth=2`` is classic double buffering.
        Returns a :class:`DevicePrefetcher`; checkpoint through ITS
        ``state()`` while it is active (buffered batches count as
        not yet emitted)::

            pf = loader.prefetch_to_device(2)
            for batch in pf:
                step(batch)
            ckpt = pf.state()
        """
        return DevicePrefetcher(self, depth, device)

    # -- health --------------------------------------------------------------

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @property
    def shuffle_window(self) -> int:
        """The effective window (0 when shuffling is off or degenerate)."""
        return self._window

    @property
    def batches_per_epoch(self) -> int:
        """Batches the NEXT epoch will emit (under salvage this shrinks
        as quarantined units are discovered)."""
        return self._n_batches

    @property
    def salvage_report(self) -> Optional[SalvageReport]:
        """Dataset-level :class:`SalvageReport` fold — per-unit reports
        merged once each, in first-delivery order (None unless
        ``ReaderOptions(salvage=True)``)."""
        return self._salvage_report

    @property
    def quarantined_units(self):
        """Sorted ``(file_index, group_index)`` units the loader dropped
        whole (geometry-changing salvage damage); rides ``state()``."""
        return sorted(self._quarantined)

    @property
    def rows_per_epoch(self) -> int:
        """Real rows per epoch in THIS host's shard."""
        return self._shard_rows

    @property
    def epoch_reports(self) -> List[trace.ScanReport]:
        """One :class:`~parquet_floor_tpu.utils.trace.ScanReport` per
        COMPLETED epoch — counters/stages as delta snapshots of the
        loader's tracer, gauges from a per-epoch
        :meth:`~parquet_floor_tpu.utils.trace.Tracer.gauge_window`
        (empty dicts unless that tracer is enabled)."""
        return list(self._epoch_reports)

    def report(self) -> trace.ScanReport:
        """The dataset-level summary: completed epochs' reports folded
        through ``ScanReport.merge`` (the same merge per-host reports
        use); before any epoch completes, a whole-run snapshot."""
        if self._epoch_reports:
            return trace.ScanReport.merge(self._epoch_reports)
        return self._tracer.scan_report(
            budget_bytes=(
                self._scan.prefetch_bytes if self._engine == "host" else None
            )
        )

    def close(self) -> None:
        """Abandon the current epoch stream (drains scan workers and
        closes files); idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._gen is not None:
            self._gen.close()
            self._gen = None
        if self._gw is not None:
            self._gw.close()
            self._gw = None
        if self._hw is not None:
            self._hw.close()
            self._hw = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
