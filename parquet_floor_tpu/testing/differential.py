"""Differential corruption-fuzz harness — the proof that salvage is a
property of the SYSTEM, not of one code path.

Four faces read the same corpus (``docs/robustness.md``):

* ``sequential`` — per-file ``ParquetFileReader`` loop (the reference
  detector; every tier's quarantine decision is made here);
* ``host_scan`` — ``scan.DatasetScanner`` (worker-thread decode,
  per-unit report merge);
* ``device_scan`` — ``scan.scan_device_groups`` (TPU engine pipeline,
  host-delegated salvage decode, placeholder columns);
* ``loader`` — ``data.DataLoader`` (unit-level quarantine, fixed-shape
  batches).

:func:`differential_case` seeds deterministic bit flips into a clean
corpus, replays the damage through the requested faces under a SIGALRM
time limit, and asserts the contract the fuzz exists to pin:

* no hang, no non-taxonomy crash — damage either salvages or raises
  ``ParquetError`` (and if ONE face deems a case fatal, every face
  must);
* **identical quarantine sets** — every face loses exactly the same
  units, down to ``(file, row_group, column, page, kind)``;
* **identical surviving bytes** — the decoded remainder is
  bit-identical across faces;
* **no silent divergence on undamaged data** — any (group, column)
  with no recorded skip must match the CLEAN corpus decode exactly on
  the group's surviving rows, with ``pyarrow`` as the independent
  oracle when it is importable (our own clean decode otherwise).

The loader face's contract is unit-level: its quarantined units must be
exactly the geometry-damaged groups of the sequential face, and its
batch stream must equal the surviving units' rows re-sliced — nothing
dropped beyond the quarantine, nothing duplicated.
"""

from __future__ import annotations

import contextlib
import os
import pathlib
import signal
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..format.encodings.plain import ByteArrayColumn
from ..format.file_read import ParquetFileReader, ReaderOptions
from ..errors import ParquetError

__all__ = [
    "CaseOutcome",
    "differential_case",
    "time_limit",
    "write_reference_corpus",
    "materialize_case",
]

DEFAULT_TIMEOUT_S = 30.0


class CaseTimeout(Exception):
    """A face exceeded the per-case SIGALRM budget (a hang, by fiat)."""


@contextlib.contextmanager
def time_limit(seconds: float):
    """SIGALRM-backed hard per-case timeout (main thread only)."""
    def _handler(signum, frame):
        raise CaseTimeout()

    old = signal.signal(signal.SIGALRM, _handler)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


# ---------------------------------------------------------------------------
# corpus + corruption
# ---------------------------------------------------------------------------

def write_reference_corpus(dir_path, n_files: int = 3, *,
                           rows_per_file: int = 1200, groups: int = 3,
                           page_values: int = 100, seed: int = 17):
    """A small multi-file corpus exercising every salvage tier's
    terrain: REQUIRED ints/doubles (row-mask tier), OPTIONAL strings
    with repeating values (dictionary pages + page-null tier), OPTIONAL
    doubles (page-null tier).  CRC on (the writer default), SNAPPY."""
    from .. import ParquetFileWriter, WriterOptions, types

    schema = types.message(
        "t",
        types.required(types.INT64).named("k"),
        types.optional(types.DOUBLE).named("v"),
        types.optional(types.BYTE_ARRAY).as_(types.string()).named("s"),
        types.required(types.DOUBLE).named("d"),
    )
    rng = np.random.default_rng(seed)
    per = rows_per_file // groups
    pathlib.Path(dir_path).mkdir(parents=True, exist_ok=True)
    paths = []
    for fi in range(n_files):
        p = os.path.join(os.fspath(dir_path), f"ref{fi}.parquet")
        with ParquetFileWriter(p, schema, WriterOptions(
            row_group_rows=per, data_page_values=page_values,
        )) as w:
            for lo in range(0, rows_per_file, per):
                n = min(per, rows_per_file - lo)
                w.write_columns({
                    "k": np.arange(lo, lo + n, dtype=np.int64)
                    + fi * 1_000_000,
                    "v": [
                        None if i % 9 == 0 else float(x)
                        for i, x in enumerate(rng.standard_normal(n))
                    ],
                    "s": [
                        None if i % 7 == 0 else f"s{(i * 13 + fi) % 41}"
                        for i in range(lo, lo + n)
                    ],
                    "d": rng.standard_normal(n),
                })
        paths.append(p)
    return paths


def case_flips(paths: Sequence[str], case_seed: int,
               footer_tail: int = 2048) -> Dict[int, List[Tuple[int, int]]]:
    """Deterministic per-file single-bit flips for one case.  Most
    seeds aim at page bytes (the region salvage can absorb); every 7th
    seed may hit anywhere, footer included — those cases pin the
    all-faces-agree-on-fatal contract."""
    rng = np.random.default_rng(case_seed)
    out: Dict[int, List[Tuple[int, int]]] = {}
    n_flips = int(rng.integers(1, 4))
    for _ in range(n_flips):
        fi = int(rng.integers(0, len(paths)))
        size = pathlib.Path(paths[fi]).stat().st_size
        if case_seed % 7 == 6:
            off = int(rng.integers(0, size))
        else:
            off = int(rng.integers(0, max(1, size - footer_tail)))
        bit = 1 << int(rng.integers(0, 8))
        out.setdefault(fi, []).append((off, bit))
    return out


def materialize_case(paths: Sequence[str], case_seed: int, out_dir):
    """Byte-flipped copies of ``paths`` for one case (files without
    flips are shared, not copied — the faces open them read-only)."""
    flips = case_flips(paths, case_seed)
    pathlib.Path(out_dir).mkdir(parents=True, exist_ok=True)
    out = []
    for fi, p in enumerate(paths):
        if fi not in flips:
            out.append(p)
            continue
        data = bytearray(pathlib.Path(p).read_bytes())
        for off, bit in flips[fi]:
            data[off] ^= bit
        q = pathlib.Path(out_dir) / f"case{case_seed}_f{fi}.parquet"
        q.write_bytes(bytes(data))
        out.append(str(q))
    return out, flips


# ---------------------------------------------------------------------------
# canonicalization (face-neutral cells)
# ---------------------------------------------------------------------------

def _cells_host(batch_col) -> tuple:
    """One host ColumnBatch → a tuple of per-row cells (None at
    nulls); floats stay exact (same decoded bits on every face)."""
    dense, mask = batch_col.dense()
    if isinstance(dense, ByteArrayColumn):
        offs = np.asarray(dense.offsets)
        data = np.asarray(dense.data).tobytes()
        vals = [
            data[offs[i]:offs[i + 1]] for i in range(len(offs) - 1)
        ]
    else:
        vals = np.asarray(dense).tolist()
    if mask is None:
        return tuple(vals)
    return tuple(
        None if m else v for v, m in zip(vals, np.asarray(mask).tolist())
    )


def _canon_host_group(batch) -> Dict[str, tuple]:
    return {
        ".".join(c.descriptor.path): _cells_host(c) for c in batch.columns
    }


def _cells_device(dc) -> tuple:
    """One DeviceColumn → per-row cells (device arrays cross to host
    here; DOUBLE under the 'bits' policy views back to float64 — exact)."""
    from ..format.parquet_thrift import Type as _T

    mask = None if dc.mask is None else np.asarray(dc.mask)
    if dc.lengths is not None:
        rows = np.asarray(dc.values)
        lens = np.asarray(dc.lengths)
        vals = [bytes(rows[i, : lens[i]].tobytes()) for i in range(len(lens))]
    else:
        v = np.asarray(dc.values)
        if dc.descriptor.physical_type == _T.DOUBLE and \
                v.dtype == np.int64:
            v = v.view(np.float64)
        vals = v.tolist()
    if mask is None:
        return tuple(vals)
    return tuple(None if m else v for v, m in zip(vals, mask.tolist()))


def _quarantine_keys(fi: int, report) -> frozenset:
    return frozenset((fi,) + s.key() for s in report.skips)


# ---------------------------------------------------------------------------
# the four faces
# ---------------------------------------------------------------------------

class FaceResult:
    """One face's outcome: ``fatal`` (the ParquetError string) or the
    quarantine-key set + canonical surviving groups."""

    def __init__(self, fatal: Optional[str] = None):
        self.fatal = fatal
        self.quarantine: frozenset = frozenset()
        self.groups: Dict[Tuple[int, int], Dict[str, tuple]] = {}


def run_sequential(paths, opts: ReaderOptions) -> FaceResult:
    res = FaceResult()
    keys = set()
    try:
        for fi, p in enumerate(paths):
            with ParquetFileReader(p, options=opts) as r:
                for gi in range(len(r.row_groups)):
                    res.groups[(fi, gi)] = _canon_host_group(
                        r.read_row_group(gi)
                    )
                keys |= set(_quarantine_keys(fi, r.salvage_report))
    except ParquetError as e:
        return FaceResult(fatal=type(e).__name__)
    res.quarantine = frozenset(keys)
    return res


def run_ranged(paths, opts: ReaderOptions,
               request: Optional[Tuple[int, int]] = (10, 60)) -> FaceResult:
    """The RANGED face (``read_row_group_ranges``).  ``request=None``
    asks for every group's FULL row range: the cover equals the group,
    so under salvage the quarantine set and surviving bytes must equal
    the sequential face's EXACTLY.  A partial ``request`` keeps its
    I/O-pruned page cover even under salvage (docs/scan.md): only a
    quarantined chunk's spans widen, damage outside the cover is never
    probed — so its quarantine is a SUBSET of the sequential face's,
    never a superset, and never a different verdict on a probed chunk
    (the precise partial-cover laws are pinned in test_salvage.py)."""
    res = FaceResult()
    keys = set()
    try:
        for fi, p in enumerate(paths):
            with ParquetFileReader(p, options=opts) as r:
                for gi in range(len(r.row_groups)):
                    if request is None:
                        nr = int(r.row_groups[gi].num_rows or 0)
                        req = [(0, nr)]
                    else:
                        req = [request]
                    batch, _covered = r.read_row_group_ranges(gi, req)
                    res.groups[(fi, gi)] = _canon_host_group(batch)
                keys |= set(_quarantine_keys(fi, r.salvage_report))
    except ParquetError as e:
        return FaceResult(fatal=type(e).__name__)
    res.quarantine = frozenset(keys)
    return res


def run_host_scan(paths, opts: ReaderOptions) -> FaceResult:
    from ..scan import DatasetScanner

    res = FaceResult()
    keys = set()
    try:
        with DatasetScanner(list(paths), options=opts) as scanner:
            for unit in scanner:
                res.groups[(unit.file_index, unit.group_index)] = \
                    _canon_host_group(unit.batch)
                keys |= set(
                    _quarantine_keys(unit.file_index, unit.salvage)
                )
    except ParquetError as e:
        return FaceResult(fatal=type(e).__name__)
    res.quarantine = frozenset(keys)
    return res


def run_device_scan(paths, opts: ReaderOptions) -> FaceResult:
    from ..batch.columns import BatchColumn
    from ..scan import scan_device_groups

    res = FaceResult()
    reports = []
    by_path = {p: fi for fi, p in enumerate(paths)}
    try:
        for fi, gi, cols in scan_device_groups(
            list(paths), options=opts, on_salvage=reports.append,
        ):
            res.groups[(fi, gi)] = {
                name: _cells_device(dc)
                for name, dc in cols.items()
                if not (isinstance(dc, BatchColumn) and dc.quarantined)
            }
    except ParquetError as e:
        return FaceResult(fatal=type(e).__name__)
    keys = set()
    for rep in reports:
        for s in rep.skips:
            fi = by_path.get(s.path)
            assert fi is not None, f"skip with unknown path {s.path!r}"
            keys.add((fi,) + s.key())
    res.quarantine = frozenset(keys)
    return res


def run_loader(paths, opts: ReaderOptions, batch_size: int = 100):
    """The loader face: returns ``(FaceResult-without-groups, loader
    row stream as a list of per-column cell tuples, quarantined
    units)``.  The stream covers every surviving row
    (drop_remainder=False)."""
    from ..data import DataLoader

    try:
        loader = DataLoader(
            list(paths), batch_size, drop_remainder=False,
            num_epochs=1, reader_options=opts,
        )
        rows = []
        names = [s.name for s in loader._specs]
        for batch in loader:
            cols = [_cells_device(c) for c in batch.columns]
            for i in range(batch.num_valid):
                rows.append(tuple(c[i] for c in cols))
        q_units = list(loader.quarantined_units)
        rep = loader.salvage_report
        loader.close()
    except ParquetError as e:
        return FaceResult(fatal=type(e).__name__), None, None, None
    res = FaceResult()
    return res, rows, q_units, names


# ---------------------------------------------------------------------------
# the oracle
# ---------------------------------------------------------------------------

def _pyarrow_clean_groups(paths):
    """Clean-corpus decode through pyarrow (independent oracle); None
    when pyarrow is unavailable — the caller falls back to our own
    clean decode."""
    try:
        import pyarrow.parquet as pq
    except ImportError:
        return None
    out = {}
    for fi, p in enumerate(paths):
        f = pq.ParquetFile(p)
        for gi in range(f.metadata.num_row_groups):
            tbl = f.read_row_group(gi)
            group = {}
            for name in tbl.column_names:
                col = tbl.column(name).to_pylist()
                group[name] = tuple(
                    v.encode() if isinstance(v, str) else v for v in col
                )
            out[(fi, gi)] = group
    return out


# ---------------------------------------------------------------------------
# one differential case
# ---------------------------------------------------------------------------

class CaseOutcome:
    def __init__(self, seed, fatal, quarantine, n_groups):
        self.seed = seed
        self.fatal = fatal          # taxonomy name when all faces raised
        self.quarantine = quarantine
        self.n_groups = n_groups

    def __repr__(self):
        what = self.fatal or f"{len(self.quarantine)} quarantined unit(s)"
        return f"<case {self.seed}: {what}, {self.n_groups} groups>"


def differential_case(ref_paths, case_seed: int, work_dir, *,
                      faces=("sequential", "host_scan", "loader"),
                      clean_oracle=None,
                      timeout_s: float = DEFAULT_TIMEOUT_S,
                      verify_crc: bool = True) -> CaseOutcome:
    """Run one seeded corruption case through ``faces`` and assert the
    differential contract (module docstring).  ``clean_oracle`` is the
    pyarrow clean decode from :func:`_pyarrow_clean_groups` (computed
    once per corpus by the caller); None falls back to our sequential
    clean decode."""
    assert faces and faces[0] == "sequential", \
        "the sequential face is the reference detector and must run"
    paths, _flips = materialize_case(ref_paths, case_seed, work_dir)
    opts = ReaderOptions(salvage=True, verify_crc=verify_crc)

    with time_limit(timeout_s):
        ref = run_sequential(paths, opts)
    results = {"sequential": ref}
    loader_stream = None
    for face in faces[1:]:
        with time_limit(timeout_s):
            if face == "host_scan":
                results[face] = run_host_scan(paths, opts)
            elif face == "device_scan":
                results[face] = run_device_scan(paths, opts)
            elif face == "loader":
                res, rows, q_units, names = run_loader(paths, opts)
                results[face] = res
                loader_stream = (rows, q_units, names)
            else:
                raise ValueError(f"unknown face {face!r}")

    # fatality must be unanimous: a case one face survives and another
    # dies on is a divergence, not a judgment call
    if ref.fatal is not None:
        for face, r in results.items():
            assert r.fatal is not None, (
                f"seed {case_seed}: sequential died ({ref.fatal}) but "
                f"{face} survived"
            )
        return CaseOutcome(case_seed, ref.fatal, frozenset(), 0)
    for face, r in results.items():
        assert r.fatal is None, (
            f"seed {case_seed}: {face} died ({r.fatal}) but sequential "
            "survived"
        )

    # identical quarantine sets + identical surviving bytes
    for face in ("host_scan", "device_scan"):
        r = results.get(face)
        if r is None:
            continue
        assert r.quarantine == ref.quarantine, (
            f"seed {case_seed}: {face} quarantine set diverged:\n"
            f"  only-{face}: {sorted(r.quarantine - ref.quarantine)}\n"
            f"  only-sequential: {sorted(ref.quarantine - r.quarantine)}"
        )
        assert set(r.groups) == set(ref.groups), (
            f"seed {case_seed}: {face} delivered different groups"
        )
        for key in ref.groups:
            assert r.groups[key] == ref.groups[key], (
                f"seed {case_seed}: {face} group {key} bytes diverged"
            )

    # undamaged (group, column) units must equal the CLEAN corpus decode
    # on the group's surviving rows — silence here is the bug class the
    # whole harness exists for
    oracle = clean_oracle
    if oracle is None:
        oracle = {}
        for fi, p in enumerate(ref_paths):
            with ParquetFileReader(p) as r:
                for gi in range(len(r.row_groups)):
                    oracle[(fi, gi)] = _canon_host_group(
                        r.read_row_group(gi)
                    )
    # quarantine keys are (file, row_group, column, page, kind)
    damaged_cols = {
        (f, rg, col) for (f, rg, col, _pg, _kind) in ref.quarantine
    }
    for (fi, gi), group in ref.groups.items():
        clean = oracle[(fi, gi)]
        n_clean = len(next(iter(clean.values())))
        keep = np.ones(n_clean, dtype=bool)
        if any(
            f == fi and rg == gi and kind == "row_mask"
            for (f, rg, _c, _pg, kind) in ref.quarantine
        ):
            # re-derive the surviving rows from a fresh salvage decode's
            # recorded spans (the spans are not part of the key set);
            # same verify_crc as the faces — a CRC-only-detectable span
            # must not enter the oracle mask when the faces kept it
            keep = _surviving_rows(paths[fi], gi, n_clean,
                                   verify_crc=verify_crc)
        for col, cells in group.items():
            if (fi, gi, col) in damaged_cols:
                continue  # damaged columns' semantics are tier tests' job
            want = tuple(
                v for v, k in zip(clean[col], keep.tolist()) if k
            )
            assert cells == want, (
                f"seed {case_seed}: UNDAMAGED column {col} of group "
                f"({fi}, {gi}) diverged from the clean decode"
            )

    # the loader face: quarantined units == geometry-damaged groups,
    # stream == surviving units' rows re-sliced
    if loader_stream is not None:
        rows, q_units, names = loader_stream
        geo = set()
        for (fi, rg, _col, _pg, kind) in ref.quarantine:
            if kind in ("chunk", "row_mask"):
                geo.add((fi, rg))
        assert set(map(tuple, q_units)) == geo, (
            f"seed {case_seed}: loader quarantined {q_units}, expected "
            f"{sorted(geo)}"
        )
        want_rows = []
        for (fi, gi) in sorted(ref.groups):
            if (fi, gi) in geo:
                continue
            g = ref.groups[(fi, gi)]
            n = len(next(iter(g.values()))) if g else 0
            for i in range(n):
                want_rows.append(tuple(g[name][i] for name in names))
        assert rows == want_rows, (
            f"seed {case_seed}: loader stream diverged from surviving "
            f"units ({len(rows)} vs {len(want_rows)} rows)"
        )

    return CaseOutcome(
        case_seed, None, ref.quarantine, len(ref.groups)
    )


def _surviving_rows(path, gi, n_clean, verify_crc: bool = True
                    ) -> np.ndarray:
    """Boolean keep-mask of group ``gi``'s rows after the row-mask
    tier, re-derived from a fresh salvage decode's recorded spans
    (same ``verify_crc`` the faces decoded under)."""
    keep = np.ones(n_clean, dtype=bool)
    with ParquetFileReader(
        path, options=ReaderOptions(salvage=True, verify_crc=verify_crc)
    ) as r:
        r.read_row_group(gi)
        for s in r.salvage_report.skips:
            if s.row_group == gi and s.kind == "row_mask" and s.row_span:
                a, b = s.row_span
                keep[max(0, int(a)):max(0, min(n_clean, int(b)))] = False
    return keep
