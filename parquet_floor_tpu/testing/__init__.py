"""Fault-injection harness — deterministic hostile-storage simulation.

SURVEY.md §5: the reference *swallows* I/O errors; this framework fails
loudly — and this package is how that promise is *proved* rather than
assumed.  :class:`FaultInjectingSource` wraps any positional source (a
``FileSource``, a path, or raw bytes) and injects, deterministically from a
seed:

* **bit flips** at explicit ``(offset, mask)`` pairs (or random ones from
  :meth:`FaultInjectingSource.random_flips`),
* **truncation** — the file appears to end at ``truncate_at``,
* **transient OSErrors** — a seeded per-read probability, optionally capped
  so retries (``ReaderOptions(io_retries=N)``) eventually succeed,
* **short reads** — a seeded probability that a read returns truncated.

Downstream users can harden their own pipelines the same way the test
suite does::

    from parquet_floor_tpu.testing import FaultInjectingSource
    from parquet_floor_tpu import ParquetFileReader, ReaderOptions

    src = FaultInjectingSource("data.parquet", seed=7,
                               transient_error_rate=0.2,
                               max_transient_failures=3)
    with ParquetFileReader(src, options=ReaderOptions(io_retries=4)) as r:
        batch = r.read_row_group(0)   # survives the injected flakiness

Determinism contract: identical construction arguments + identical sequence
of ``read_at`` calls ⇒ identical injected faults.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from ..errors import TruncatedFileError
from ..io.source import FileSource, RetryingSource  # noqa: F401  (re-export)
from .remote import (  # noqa: F401  (re-export)
    RemoteProfile,
    SimulatedRemoteSource,
    SimulatedRemoteTransport,
)

__all__ = [
    "FaultInjectingSource",
    "RetryingSource",
    "RemoteProfile",
    "SimulatedRemoteSource",
    "SimulatedRemoteTransport",
]


class FaultInjectingSource:
    """Deterministic, seeded fault-injection wrapper over a source.

    Parameters
    ----------
    source:
        A ``FileSource``-like object (anything with ``read_at``/``size``),
        or a path / bytes, which are wrapped in a ``FileSource``.
    seed:
        Seed for the probability draws (transient errors, short reads).
    bit_flips:
        Iterable of ``(offset, xor_mask)`` pairs applied to any read that
        covers ``offset``.  The underlying bytes are never mutated — reads
        are copied before flipping.
    truncate_at:
        Virtual end-of-file: the source reports ``min(size, truncate_at)``
        and reads past it raise
        :class:`~parquet_floor_tpu.errors.TruncatedFileError`.
    transient_error_rate:
        Per-``read_at`` probability of raising ``OSError`` (the transient
        class ``ReaderOptions(io_retries=...)`` retries).
    max_transient_failures:
        Cap on total injected OSErrors; None = unlimited.  Set it to let a
        bounded retry loop eventually win.
    short_read_rate:
        Per-``read_at`` probability of simulating a short read (surfaced
        as ``TruncatedFileError``, exactly what ``FileSource`` raises when
        the filesystem returns fewer bytes than asked).
    """

    def __init__(
        self,
        source,
        *,
        seed: int = 0,
        bit_flips: Iterable[Tuple[int, int]] = (),
        truncate_at: Optional[int] = None,
        transient_error_rate: float = 0.0,
        max_transient_failures: Optional[int] = None,
        short_read_rate: float = 0.0,
    ):
        self._inner = source if hasattr(source, "read_at") else FileSource(source)
        self._rng = np.random.default_rng(seed)
        self._flips: List[Tuple[int, int]] = [
            (int(o), int(m) & 0xFF) for o, m in bit_flips
        ]
        self._truncate_at = truncate_at
        self._transient_rate = float(transient_error_rate)
        self._transient_budget = max_transient_failures
        self._short_read_rate = float(short_read_rate)
        # observability for assertions in harness tests
        self.reads = 0
        self.injected_transients = 0
        self.injected_short_reads = 0
        self.injected_flips = 0

    @staticmethod
    def random_flips(size: int, n: int, seed: int) -> List[Tuple[int, int]]:
        """``n`` deterministic single-bit flips over a ``size``-byte file:
        the standard corruption pattern for the fuzz smoke test."""
        rng = np.random.default_rng(seed)
        offsets = rng.integers(0, size, n)
        bits = rng.integers(0, 8, n)
        return [(int(o), 1 << int(b)) for o, b in zip(offsets, bits)]

    @property
    def name(self) -> str:
        return f"fault-injecting({self._inner.name})"

    @property
    def size(self) -> int:
        if self._truncate_at is None:
            return self._inner.size
        return min(self._inner.size, int(self._truncate_at))

    def _draw(self, rate: float) -> bool:
        return rate > 0.0 and float(self._rng.random()) < rate

    def read_at(self, offset: int, length: int) -> memoryview:
        self.reads += 1
        if offset < 0 or offset + length > self.size:
            raise TruncatedFileError(
                f"read [{offset}, {offset + length}) outside "
                f"(injected-truncation) file of {self.size} bytes",
                path=self.name, offset=offset,
            )
        if self._draw(self._transient_rate) and (
            self._transient_budget is None or
            self.injected_transients < self._transient_budget
        ):
            self.injected_transients += 1
            raise OSError(
                f"injected transient I/O error "
                f"(#{self.injected_transients} at offset {offset})"
            )
        if self._draw(self._short_read_rate):
            self.injected_short_reads += 1
            raise TruncatedFileError(
                f"injected short read: wanted {length}, got {length // 2}",
                path=self.name, offset=offset,
            )
        data = self._inner.read_at(offset, length)
        hits = [
            (o - offset, m) for o, m in self._flips
            if offset <= o < offset + length
        ]
        if not hits:
            return data
        buf = bytearray(data)
        for rel, mask in hits:
            buf[rel] ^= mask
            self.injected_flips += 1
        return memoryview(bytes(buf))

    def close(self) -> None:
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
