"""Seeded remote-storage simulator — every object-store failure mode,
deterministically, in CI.

:class:`SimulatedRemoteSource` is a full :class:`io.remote.RemoteSource`
(hedging, circuit breaker, classification — the real code paths) over a
:class:`SimulatedRemoteTransport` that models the network:

* **per-request latency**: ``base + uniform(jitter)`` plus, with
  probability ``tail_p``, a heavy-tail excursion in ``[tail_latency_s,
  3*tail_latency_s]`` — the straggler distribution hedged reads exist
  for;
* **bandwidth cap**: ``length / bandwidth_bytes_per_s`` added per
  request;
* **throttling windows**: a token bucket (``throttle_rps`` refill,
  ``throttle_burst`` capacity) — an over-rate request raises
  :class:`~parquet_floor_tpu.errors.RemoteThrottledError` with the
  bucket's real ``retry_after_s``;
* **injected faults**: a seeded per-request transient ``OSError``
  probability (``fault_rate``), plus an ``outage_s`` window — every
  request in the first ``outage_s`` seconds after the transport's first
  request fails transient, the deterministic way to trip the circuit
  breaker and prove retry recovery.

Determinism contract (the CI promise): the random draws — latency,
tail, fault — are KEYED, not sequential: each is derived from ``(seed,
offset, length, k)`` where ``k`` counts the requests for that exact
range that REACHED the latency/fault model (0 = first modeled attempt,
1 = the hedge or first retry, …).  Thread scheduling therefore cannot
change which ranges are slow or which fail: two runs over the same scan
see the same tail set and the same fault set, whatever order the pool
issued requests in.  Only the wall-clock features (the outage window,
the throttle bucket) depend on real time — their refusals do NOT
consume ordinals (a throttled attempt re-draws with the same ``k`` on
retry), so timing can only change when a request is refused, never
which modeled attempts fault or what bytes come back.

Scripted overrides pin exact scenarios (the hedging/breaker edge-case
tests): ``latency_overrides[(offset, k)] = seconds`` replaces the drawn
latency, ``fault_overrides[(offset, k)] = exc_or_message`` raises after
the latency elapses (a slow THEN failed request, like real timeouts).

Example::

    from parquet_floor_tpu.testing import SimulatedRemoteSource, RemoteProfile

    src = SimulatedRemoteSource(
        "data.parquet", seed=7,
        profile=RemoteProfile(base_latency_s=0.02, jitter_s=0.002,
                              tail_p=0.1, tail_latency_s=0.08,
                              fault_rate=0.05),
    )
    with ParquetFileReader(src, options=ReaderOptions(io_retries=4)) as r:
        batch = r.read_row_group(0)   # survives the simulated store
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union

import numpy as np

from ..errors import RemoteThrottledError
from ..io.remote import RemoteSource
from ..io.source import FileSource


@dataclass(frozen=True)
class RemoteProfile:
    """One remote store's behavior model (module docstring).  All-zero
    defaults are a perfect store — add pathologies per test/bench leg."""

    base_latency_s: float = 0.0
    jitter_s: float = 0.0
    tail_p: float = 0.0
    tail_latency_s: float = 0.0
    bandwidth_bytes_per_s: Optional[float] = None
    fault_rate: float = 0.0
    outage_s: float = 0.0
    throttle_rps: Optional[float] = None
    throttle_burst: int = 8

    def __post_init__(self):
        for name in ("base_latency_s", "jitter_s", "tail_p",
                     "tail_latency_s", "fault_rate", "outage_s"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        if self.tail_p > 1 or self.fault_rate > 1:
            raise ValueError("tail_p / fault_rate are probabilities (<= 1)")
        if self.bandwidth_bytes_per_s is not None \
                and self.bandwidth_bytes_per_s <= 0:
            raise ValueError("bandwidth_bytes_per_s must be > 0 (or None)")
        if self.throttle_rps is not None and self.throttle_rps <= 0:
            raise ValueError("throttle_rps must be > 0 (or None)")
        if self.throttle_burst < 1:
            raise ValueError("throttle_burst must be >= 1")


class SimulatedRemoteTransport:
    """The transport half of the simulator: one ranged GET over a local
    source, with the profile's latency/fault model applied (module
    docstring).  Thread-safe; counters (``requests``, ``faults``,
    ``throttles``, ``bytes_served``, ``tail_requests``) are for test
    assertions."""

    def __init__(self, source, profile: RemoteProfile = RemoteProfile(),
                 seed: int = 0,
                 latency_overrides: Optional[Dict[Tuple[int, int], float]] = None,
                 fault_overrides: Optional[
                     Dict[Tuple[int, int], Union[BaseException, str]]
                 ] = None,
                 sleep=time.sleep, clock=time.monotonic):
        self._inner = (
            source if hasattr(source, "read_at") else FileSource(source)
        )
        self.profile = profile
        self.seed = int(seed)
        self._latency_overrides = dict(latency_overrides or {})
        self._fault_overrides = dict(fault_overrides or {})
        self._sleep = sleep
        self._clock = clock
        self._lock = threading.Lock()
        self._ordinal: Dict[Tuple[int, int], int] = {}  # (offset, length) -> k
        self._first_request_at: Optional[float] = None
        self._tokens = float(profile.throttle_burst)
        self._tokens_at: Optional[float] = None
        self.requests = 0
        self.faults = 0
        self.throttles = 0
        self.tail_requests = 0
        self.bytes_served = 0

    @property
    def name(self) -> str:
        return f"simulated-remote({self._inner.name})"

    @property
    def size(self) -> int:
        return self._inner.size

    def _admit(self, offset: int, length: int):
        """Book-keeping under the lock: the outage window, the throttle
        bucket, and — only for requests that reach the latency/fault
        model — the per-range ordinal.  Returns ``(k, outage,
        retry_after)``; ``k`` is None when the request was refused.
        Refused attempts must NOT consume ordinals: whether an attempt
        hits the outage window or the token bucket is wall-clock
        dependent, and letting it shift the keyed draws would break the
        determinism contract (the same range would fault on one run and
        not the other)."""
        now = self._clock()
        with self._lock:
            self.requests += 1
            if self._first_request_at is None:
                self._first_request_at = now
            if (
                self.profile.outage_s > 0
                and now - self._first_request_at < self.profile.outage_s
            ):
                return None, True, None
            rps = self.profile.throttle_rps
            if rps is not None:
                if self._tokens_at is not None:
                    self._tokens = min(
                        float(self.profile.throttle_burst),
                        self._tokens + (now - self._tokens_at) * rps,
                    )
                self._tokens_at = now
                if self._tokens < 1.0:
                    self.throttles += 1
                    return None, False, (1.0 - self._tokens) / rps
                self._tokens -= 1.0
            key = (int(offset), int(length))
            k = self._ordinal.get(key, 0)
            self._ordinal[key] = k + 1
            return k, False, None

    def get_range(self, offset: int, length: int) -> bytes:
        k, outage, retry_after = self._admit(offset, length)
        if outage:
            with self._lock:
                self.faults += 1
            raise OSError(
                f"simulated outage: request for "
                f"[{offset}, {offset + length}) refused"
            )
        if retry_after is not None:
            raise RemoteThrottledError(
                f"simulated throttle: over {self.profile.throttle_rps} rps",
                retry_after_s=retry_after, path=self.name, offset=offset,
            )
        p = self.profile
        # keyed draws: (seed, offset, length, k) — thread scheduling can
        # never change which ranges are slow or which fail
        rng = np.random.default_rng(
            [self.seed, int(offset), int(length), int(k)]
        )
        lat = p.base_latency_s + p.jitter_s * float(rng.random())
        is_tail = p.tail_p > 0 and float(rng.random()) < p.tail_p
        if is_tail:
            lat += p.tail_latency_s * (1.0 + 2.0 * float(rng.random()))
            with self._lock:
                self.tail_requests += 1
        if p.bandwidth_bytes_per_s:
            lat += length / p.bandwidth_bytes_per_s
        fault: Union[BaseException, str, None] = None
        if (int(offset), k) in self._fault_overrides:
            fault = self._fault_overrides[(int(offset), k)]
        elif p.fault_rate > 0 and float(rng.random()) < p.fault_rate:
            fault = (
                f"simulated transient fault (offset={offset}, attempt={k})"
            )
        lat = self._latency_overrides.get((int(offset), k), lat)
        if lat > 0:
            self._sleep(lat)
        if fault is not None:
            with self._lock:
                self.faults += 1
            if isinstance(fault, BaseException):
                raise fault
            raise OSError(fault)
        data = bytes(self._inner.read_at(offset, length))
        with self._lock:
            self.bytes_served += length
        return data

    def close(self) -> None:
        self._inner.close()


class SimulatedRemoteSource(RemoteSource):
    """A :class:`~parquet_floor_tpu.io.remote.RemoteSource` over a
    :class:`SimulatedRemoteTransport` — the one-liner the tests, the
    bench's cold-storage leg, and the CI remote smoke construct.  The
    transport is exposed as ``self.transport`` for fault/latency
    assertions; every ``RemoteSource`` knob (hedging, breaker, deadline)
    passes through as keyword arguments."""

    def __init__(self, source, *, profile: RemoteProfile = RemoteProfile(),
                 seed: int = 0, latency_overrides=None, fault_overrides=None,
                 sleep=time.sleep, clock=time.monotonic, **remote_kwargs):
        transport = SimulatedRemoteTransport(
            source, profile, seed,
            latency_overrides=latency_overrides,
            fault_overrides=fault_overrides,
            sleep=sleep, clock=clock,
        )
        try:
            super().__init__(transport, clock=clock, **remote_kwargs)
        except BaseException:
            transport.close()
            raise
        self.transport = transport
