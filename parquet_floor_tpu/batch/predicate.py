"""Statistics-based row-group pushdown — a native win over the reference.

The reference streams every row group unconditionally (its ``trySplit``
declines even to parallelize — ``ParquetReader.java:214-217``) and exposes
footer statistics only as raw metadata.  Here a small predicate DSL
evaluates against each row group's column chunk min/max/null_count
statistics, so scans skip groups that *cannot* contain a match before a
single page is read or shipped:

    from parquet_floor_tpu.batch.predicate import col
    pred = (col("l_shipdate") >= 9000) & (col("l_quantity") < 10.0)
    keep = pred.row_groups(reader)         # indices that MAY match
    for i in keep:
        batch = reader.read_row_group(i)   # or TpuRowGroupReader

Semantics are conservative three-valued logic: a group is kept unless the
statistics *prove* no row can match (absent/undecodable stats keep the
group).  Float NaN never participates in min/max (writer skips NaNs), so
ordered comparisons remain sound.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..format.parquet_thrift import Type

_NUMPY_DTYPE = {
    Type.INT32: np.int32,
    Type.INT64: np.int64,
    Type.FLOAT: np.float32,
    Type.DOUBLE: np.float64,
}


def _decode_stat(pt: int, raw: Optional[bytes]):
    """Decode a min/max statistics value per physical type; None = unknown."""
    if raw is None:
        return None
    if pt in _NUMPY_DTYPE:
        dt = np.dtype(_NUMPY_DTYPE[pt])
        if len(raw) != dt.itemsize:
            return None
        return np.frombuffer(raw, dtype=dt)[0].item()
    if pt == Type.BOOLEAN:
        return bool(raw[0]) if len(raw) == 1 else None
    if pt == Type.BYTE_ARRAY or pt == Type.FIXED_LEN_BYTE_ARRAY:
        return bytes(raw)
    return None  # INT96 etc: no usable order


@dataclass(frozen=True)
class _ChunkStats:
    min: object          # decoded or None
    max: object
    null_count: Optional[int]
    num_values: Optional[int]


def _chunk_stats(rg, name: str) -> Optional[_ChunkStats]:
    chunk = _find_chunk(rg, name)
    if chunk is None:
        return None
    st = chunk.meta_data.statistics
    if st is None:
        return None
    pt = chunk.meta_data.type
    # Legacy Statistics.min/max were written with signed byte comparison
    # (and PARQUET-251 made them outright wrong for binary), so for
    # BYTE_ARRAY/FLBA only the new min_value/max_value fields are
    # trustworthy; treat legacy-only binary stats as unknown (keep the
    # group), matching parquet-mr's StatisticsFilter.
    binary = pt in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY)
    raw_mn = st.min_value if st.min_value is not None else (None if binary else st.min)
    raw_mx = st.max_value if st.max_value is not None else (None if binary else st.max)
    mn = _decode_stat(pt, raw_mn)
    mx = _decode_stat(pt, raw_mx)
    return _ChunkStats(mn, mx, st.null_count, chunk.meta_data.num_values)


def _coerce(value, other):
    """Make a user literal comparable with a decoded stat (str → bytes;
    surrogateescape so a key round-tripped from a non-UTF8 row cell
    compares against its original bytes instead of raising)."""
    if isinstance(value, str) and isinstance(other, bytes):
        return value.encode("utf-8", "surrogateescape")
    return value


class Predicate:
    """Base: ``may_match(rg) -> bool`` (True = cannot be ruled out)."""

    def may_match(self, rg) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    def may_match_with(self, reader, rg) -> bool:
        """Like :meth:`may_match` but with file access: equality
        predicates additionally consult the chunk's Bloom filter when
        the min/max statistics cannot rule the group out."""
        return self.may_match(rg)

    def row_groups(self, reader) -> List[int]:
        """Indices of row groups that may contain matching rows."""
        return [
            i for i, rg in enumerate(reader.row_groups)
            if self.may_match_with(reader, rg)
        ]

    def row_ranges(self, reader, rg_index: int) -> List[tuple]:
        """Half-open row ranges within a row group that may match, pruned
        with the page indexes (ColumnIndex/OffsetIndex) when present.

        Conservative like :meth:`row_groups`: rows are dropped only when
        page statistics *prove* they cannot match; a column without page
        indexes contributes the whole group."""
        rg = reader.row_groups[rg_index]
        n = int(rg.num_rows or 0)
        return normalize_ranges(self._ranges(reader, rg, n), n)

    def _ranges(self, reader, rg, n: int) -> List[tuple]:
        return [(0, n)]

    def __and__(self, other: "Predicate") -> "Predicate":
        return _And(self, other)

    def __or__(self, other: "Predicate") -> "Predicate":
        return _Or(self, other)

    def __invert__(self) -> "Predicate":
        # NOT over three-valued logic cannot reuse may_match (both a
        # predicate and its negation may be satisfiable in one group);
        # each comparison supplies its own negation instead.
        raise TypeError(
            "use the negated comparison (e.g. col('x') != 3) rather than ~"
        )


def normalize_ranges(ranges: List[tuple], n: int) -> List[tuple]:
    """Clip to [0, n), sort, and merge overlapping/adjacent ranges (the
    shared interval algebra for row-range pruning and selective reads)."""
    clipped = sorted(
        (max(0, int(a)), min(n, int(b))) for a, b in ranges if b > a
    )
    out: List[tuple] = []
    for a, b in clipped:
        if a >= b:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _intersect(xs: List[tuple], ys: List[tuple]) -> List[tuple]:
    out = []
    i = j = 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if a < b:
            out.append((a, b))
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return out


@dataclass(frozen=True)
class _And(Predicate):
    a: Predicate
    b: Predicate

    def may_match(self, rg) -> bool:
        return self.a.may_match(rg) and self.b.may_match(rg)

    def may_match_with(self, reader, rg) -> bool:
        return self.a.may_match_with(reader, rg) and self.b.may_match_with(
            reader, rg
        )

    def _ranges(self, reader, rg, n):
        return _intersect(
            normalize_ranges(self.a._ranges(reader, rg, n), n),
            normalize_ranges(self.b._ranges(reader, rg, n), n),
        )


@dataclass(frozen=True)
class _Or(Predicate):
    a: Predicate
    b: Predicate

    def may_match(self, rg) -> bool:
        return self.a.may_match(rg) or self.b.may_match(rg)

    def may_match_with(self, reader, rg) -> bool:
        return self.a.may_match_with(reader, rg) or self.b.may_match_with(
            reader, rg
        )

    def _ranges(self, reader, rg, n):
        return self.a._ranges(reader, rg, n) + self.b._ranges(reader, rg, n)


def _cmp_may_match(op: str, value, mn, mx, null_count) -> bool:
    """Core three-valued comparison against [mn, mx] statistics."""
    v = _coerce(value, mn if mn is not None else mx)
    try:
        if op == "==":
            if mn is not None and v < mn:
                return False
            if mx is not None and v > mx:
                return False
            return True
        if op == "!=":
            # ruled out only when every row PROVABLY equals v: bounds pin
            # a single value and the null count is known to be zero (an
            # absent null count may hide matching nulls)
            if mn is not None and mx is not None and mn == mx == v and null_count == 0:
                return False
            return True
        if op == "<":
            return mn is None or mn < v
        if op == "<=":
            return mn is None or mn <= v
        if op == ">":
            return mx is None or mx > v
        if op == ">=":
            return mx is None or mx >= v
    except TypeError:
        return True  # incomparable literal: keep
    return True


def _plain_value(pt: int, value):
    """A user literal as the one-element sequence ``hash_values`` hashes
    with the column's plain encoding."""
    if pt in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY):
        b = value.encode("utf-8") if isinstance(value, str) else bytes(value)
        return [b]
    np_t = {
        Type.INT32: np.int32, Type.INT64: np.int64,
        Type.FLOAT: np.float32, Type.DOUBLE: np.float64,
    }.get(pt)
    if np_t is None:
        raise TypeError(f"no bloom hash for physical type {pt}")
    return np.array([value], dtype=np_t)


def _find_chunk(rg, name: str):
    # Exact dotted-path match only: a bare top-level-group name must NOT
    # resolve to the group's first leaf (pruning on the wrong column's
    # stats); unresolved names fall through to None = no stats = keep.
    for chunk in rg.columns or []:
        path = chunk.meta_data.path_in_schema
        if ".".join(path) == name:
            return chunk
    return None


def _page_rows(reader, rg, n: int, name: str):
    """(chunk, column_index, per-page (row_start, row_end)) or None when
    the page indexes are unavailable."""
    from ..format.file_read import page_row_spans

    chunk = _find_chunk(rg, name)
    if chunk is None:
        return None
    ci = reader.read_column_index(chunk)
    oi = reader.read_offset_index(chunk)
    if ci is None or oi is None or not oi.page_locations:
        return None
    return chunk, ci, [(a, b) for _pl, a, b in page_row_spans(oi, n)]


@dataclass(frozen=True)
class _Cmp(Predicate):
    name: str
    op: str
    value: object

    def may_match(self, rg) -> bool:
        st = _chunk_stats(rg, self.name)
        if st is None:
            return True
        return _cmp_may_match(self.op, self.value, st.min, st.max, st.null_count)

    def may_match_with(self, reader, rg) -> bool:
        if not self.may_match(rg):
            return False
        if self.op != "==":
            return True
        # stats could not rule the group out — the Bloom filter can still
        # prove the exact value absent (no false negatives by contract)
        chunk = _find_chunk(rg, self.name)
        if chunk is None:
            return True
        try:
            bf = reader.read_bloom_filter(chunk)
        except Exception:
            return True  # malformed/foreign filter: stay conservative
        if bf is None:
            return True
        from ..format.bloom import probe_hashes

        md = chunk.meta_data
        try:
            # probe_hashes covers both ±0.0 encodings for float zeros
            # (foreign writers insert only the stored bit pattern)
            h = probe_hashes(md.type, _plain_value(md.type, self.value))
        except (TypeError, ValueError, OverflowError):
            # unhashable / out-of-range literal: stay conservative
            return True
        return bool(bf.check_hashes(h).any())

    def _ranges(self, reader, rg, n):
        pr = _page_rows(reader, rg, n, self.name)
        if pr is None:
            return [(0, n)]
        chunk, ci, pages = pr
        pt = chunk.meta_data.type
        out = []
        for i, (a, b) in enumerate(pages):
            if ci.null_pages and i < len(ci.null_pages) and ci.null_pages[i]:
                # page holds only nulls: no ordered comparison can match,
                # but "!=" keeps null rows (chunk-level convention)
                if self.op == "!=":
                    out.append((a, b))
                continue
            # a foreign/truncated ColumnIndex may carry fewer entries than
            # the OffsetIndex has pages: missing entry = unknown = keep
            mn = (
                _decode_stat(pt, ci.min_values[i] or None)
                if ci.min_values and i < len(ci.min_values)
                else None
            )
            mx = (
                _decode_stat(pt, ci.max_values[i] or None)
                if ci.max_values and i < len(ci.max_values)
                else None
            )
            nc = (
                ci.null_counts[i]
                if ci.null_counts and i < len(ci.null_counts)
                else None
            )
            if _cmp_may_match(self.op, self.value, mn, mx, nc):
                out.append((a, b))
        return out


@dataclass(frozen=True)
class _IsNull(Predicate):
    name: str
    want_null: bool

    def may_match(self, rg) -> bool:
        st = _chunk_stats(rg, self.name)
        if st is None or st.null_count is None:
            return True
        if self.want_null:
            return st.null_count > 0
        if st.num_values is None:
            return True
        return st.null_count < st.num_values

    def _ranges(self, reader, rg, n):
        pr = _page_rows(reader, rg, n, self.name)
        if pr is None:
            return [(0, n)]
        _, ci, pages = pr
        out = []
        for i, (a, b) in enumerate(pages):
            null_page = bool(
                ci.null_pages and i < len(ci.null_pages) and ci.null_pages[i]
            )
            nc = (
                ci.null_counts[i]
                if ci.null_counts and i < len(ci.null_counts)
                else None
            )
            if self.want_null:
                keep = null_page or nc is None or nc > 0
            else:
                keep = not null_page
            if keep:
                out.append((a, b))
        return out


# ---------------------------------------------------------------------------
# Predicate export + vectorized evaluation (the pushdown compilers' input)
# ---------------------------------------------------------------------------

def tree(p: Predicate) -> tuple:
    """Export a predicate as a static nested tuple — the ONE structural
    form both pushdown compilers consume (the device compute tail in
    ``tpu.compute`` and the host :func:`eval_mask` below), so filter
    semantics cannot fork between faces:

    * ``("and", a, b)`` / ``("or", a, b)``
    * ``("cmp", name, op, value)`` — ``op`` in ``== != < <= > >=``;
      string literals normalize to UTF-8 bytes
    * ``("isnull", name, want_null)``

    The tuple is hashable (literals are numbers/bytes), so it can ride a
    jit static argument — which is how a predicate becomes part of a
    fused executable's cache key.  Raises ``TypeError`` on predicates
    that cannot export (unhashable literals, foreign subclasses)."""
    if isinstance(p, _And):
        return ("and", tree(p.a), tree(p.b))
    if isinstance(p, _Or):
        return ("or", tree(p.a), tree(p.b))
    if isinstance(p, _Cmp):
        v = p.value
        if isinstance(v, str):
            # surrogateescape: a key round-tripped from a row cell (the
            # cursor stringifies non-UTF8 binary that way) must compare
            # against its original bytes, not raise
            v = v.encode("utf-8", "surrogateescape")
        if not isinstance(v, (bool, int, float, bytes)):
            raise TypeError(
                f"predicate literal {v!r} on {p.name!r} is not a "
                "number/bool/string/bytes — cannot export for pushdown"
            )
        return ("cmp", p.name, p.op, v)
    if isinstance(p, _IsNull):
        return ("isnull", p.name, p.want_null)
    raise TypeError(
        f"cannot export predicate node {type(p).__name__} for pushdown"
    )


def tree_columns(t: tuple):
    """The set of column names a :func:`tree` references."""
    if t[0] in ("and", "or"):
        return tree_columns(t[1]) | tree_columns(t[2])
    return {t[1]}


def _cmp_arrays(vals, op: str, v):
    if op == "==":
        return vals == v
    if op == "!=":
        return vals != v
    if op == "<":
        return vals < v
    if op == "<=":
        return vals <= v
    if op == ">":
        return vals > v
    return vals >= v


def eval_mask(p: Predicate, resolve, n: int) -> np.ndarray:
    """Row-exact vectorized evaluation of ``p`` over decoded columns.

    ``resolve(name)`` returns ``(values, null_mask)`` — ``values`` a
    NumPy array (numerics/bools) or an object array of ``bytes``
    (strings); ``null_mask`` is a bool array (True = null) or None for
    required columns.  Semantics are SQL-ish three-valued collapsed to
    selection: any comparison against a null cell is False (pyarrow's
    ``filter`` drop behavior), NaN follows IEEE (every ordered
    comparison False, ``!=`` True), ``is_null``/``is_not_null`` read
    the mask directly.  This is the host twin of the device compute
    tail — the lookup face's exact-match filter and the differential
    tests both ride it."""
    return _eval_tree(tree(p), resolve, n)


def _eval_tree(t: tuple, resolve, n: int) -> np.ndarray:
    kind = t[0]
    if kind == "and":
        return _eval_tree(t[1], resolve, n) & _eval_tree(t[2], resolve, n)
    if kind == "or":
        return _eval_tree(t[1], resolve, n) | _eval_tree(t[2], resolve, n)
    if kind == "isnull":
        _vals, mask = resolve(t[1])
        m = (
            np.zeros(n, bool) if mask is None
            else np.asarray(mask, dtype=bool)
        )
        return m if t[2] else ~m
    _, name, op, v = t
    vals, mask = resolve(name)
    vals = np.asarray(vals)
    if vals.dtype == object and isinstance(v, str):
        v = v.encode("utf-8", "surrogateescape")
    try:
        out = np.asarray(_cmp_arrays(vals, op, v), dtype=bool)
    except TypeError:
        # incomparable literal/column pairing: nothing matches
        out = np.zeros(n, bool)
    if out.shape != (n,):  # a scalar False from an object-array compare
        out = np.broadcast_to(out, (n,)).copy()
    if mask is not None:
        out &= ~np.asarray(mask, dtype=bool)
    return out


class Col:
    """Column reference for building predicates: ``col("x") > 3``."""

    def __init__(self, name: str):
        self._name = name

    def __eq__(self, v) -> Predicate:  # type: ignore[override]
        return _Cmp(self._name, "==", v)

    def __ne__(self, v) -> Predicate:  # type: ignore[override]
        return _Cmp(self._name, "!=", v)

    def __lt__(self, v) -> Predicate:
        return _Cmp(self._name, "<", v)

    def __le__(self, v) -> Predicate:
        return _Cmp(self._name, "<=", v)

    def __gt__(self, v) -> Predicate:
        return _Cmp(self._name, ">", v)

    def __ge__(self, v) -> Predicate:
        return _Cmp(self._name, ">=", v)

    def is_null(self) -> Predicate:
        return _IsNull(self._name, True)

    def is_not_null(self) -> Predicate:
        return _IsNull(self._name, False)

    __hash__ = None  # type: ignore[assignment]


def col(name: str) -> Col:
    return Col(name)
