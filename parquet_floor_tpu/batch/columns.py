"""Columnar batch containers — the L3 materialization layer (SURVEY.md §1:
"columnar batch materialization (arrays, not per-row events)").

Where the reference surfaces one cell at a time through ``ColumnReader``
getters (``ParquetReader.java:141-168``), this framework decodes whole row
groups into arrays and serves both:
  * per-row cursors for the Hydrator-parity API, and
  * zero-copy columnar access for batch/TPU consumers (the native win).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Union

import numpy as np

from ..format.encodings.plain import ByteArrayColumn
from ..format.schema import ColumnDescriptor


@dataclass
class ColumnBatch:
    """All values of one column across a row-group's pages.

    ``values`` holds non-null leaf values only (length = count of
    def_levels == max_def, or num_values for required columns).
    """

    descriptor: ColumnDescriptor
    num_values: int  # total level count (rows for flat columns)
    values: Union[np.ndarray, ByteArrayColumn]
    def_levels: Optional[np.ndarray] = None
    rep_levels: Optional[np.ndarray] = None

    def __post_init__(self):
        self._value_index = None

    @property
    def is_flat(self) -> bool:
        return self.descriptor.max_repetition_level == 0

    @property
    def null_mask(self) -> Optional[np.ndarray]:
        """True where the slot is null; None when column is required."""
        if self.def_levels is None:
            return None
        return self.def_levels != self.descriptor.max_definition_level

    def _ensure_value_index(self):
        if self._value_index is None and self.def_levels is not None:
            present = self.def_levels == self.descriptor.max_definition_level
            self._value_index = np.cumsum(present) - 1
        return self._value_index

    def cell(self, i: int):
        """Row-level access for flat columns; None when null.

        Null semantics parity: a cell is null iff its definition level is
        below the max (reference ``ParquetReader.java:146,165-167``).
        """
        if not self.is_flat:
            raise ValueError("cell() requires a flat (non-repeated) column")
        if self.def_levels is not None:
            if self.def_levels[i] != self.descriptor.max_definition_level:
                return None
            vi = self._ensure_value_index()[i]
        else:
            vi = i
        v = self.values[int(vi)]
        return v

    def dense(self, fill=None):
        """Dense representation: (values_with_fill, null_mask) arrays.

        Fixed-width types get a NumPy array with ``fill`` (or 0) in null
        slots; BYTE_ARRAY gets a ByteArrayColumn with empty strings at null
        slots.  This is the array that ships to the TPU.
        """
        mask = self.null_mask
        if mask is None:
            return self.values, None
        n = self.num_values
        if isinstance(self.values, ByteArrayColumn):
            lengths = np.zeros(n, dtype=np.int64)
            lengths[~mask] = self.values.lengths()
            offsets = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lengths, out=offsets[1:])
            return ByteArrayColumn(offsets, self.values.data.copy()), mask
        if self.values.ndim == 2:  # FLBA / INT96 rows
            out = np.zeros((n, self.values.shape[1]), dtype=self.values.dtype)
            out[~mask] = self.values
            return out, mask
        out = np.zeros(n, dtype=self.values.dtype)
        if fill is not None:
            out[:] = fill
        out[~mask] = self.values
        return out, mask


@dataclass
class RowGroupBatch:
    """Decoded columns of one row group, in schema (column) order."""

    columns: List[ColumnBatch]
    num_rows: int

    def column(self, top_level_name: str) -> ColumnBatch:
        for c in self.columns:
            if c.descriptor.path[0] == top_level_name:
                return c
        raise KeyError(f"no column with top-level name {top_level_name!r}")


@dataclass
class BatchColumn:
    """One decoded column of one row group, as the batch-hydration
    protocol serves it (``ParquetReader.stream_batches``) — the batch
    face of the Hydrator boundary (SURVEY.md §7 L3: "zero-copy
    batch/Arrow-style access (native win)").

    Engine-neutral contract:
      * fixed-width columns: ``values`` is a typed ``(n,)`` array —
        NumPy from the host engine, ``jax.Array`` living on device from
        the TPU engine (zero-copy on each side; DOUBLE is real float64
        either way).  FLBA/INT96 arrive as ``(n, width)`` uint8 rows.
      * strings: engine-native layout — host: a ``ByteArrayColumn``
        (int64 offsets + contiguous data, ``lengths`` = per-row bytes);
        device: ``(n, max_len)`` uint8 rows on device plus ``lengths``.
        ``bytes_list()`` / ``to_arrow()`` consume both uniformly.
      * ``mask`` is True at nulls (None for required columns).
      * repeated leaves: ``values`` is the dense non-null value stream
        and ``def_levels``/``rep_levels`` carry the Dremel levels
        (assemble via ``batch.nested.assemble_nested`` or
        ``DeviceColumn.assemble``).

    Device arrays export zero-copy through the standard DLPack protocol
    (``__dlpack__`` delegates to ``values``); ``to_arrow()`` builds a
    ``pyarrow`` array (zero-copy for host primitives and large_binary —
    device arrays cross device→host first, which is a copy by nature).
    """

    descriptor: ColumnDescriptor
    values: object
    mask: Optional[object] = None
    lengths: Optional[object] = None
    def_levels: Optional[object] = None
    rep_levels: Optional[object] = None
    # DOUBLE through the TPU engine: exact int64 bit patterns (TPU f64
    # storage is emulated and cannot hold arbitrary doubles losslessly).
    # ``to_numpy()``/``to_arrow()`` view them back to float64 on host;
    # on-device consumers get the raw bits via ``values``/DLPack.
    f64_bits: bool = False
    # salvage mode: True when this row group's chunk was quarantined —
    # ``values`` is None so positional consumers fail LOUDLY instead of
    # silently misreading a shifted column; the loss is itemized in the
    # reader's SalvageReport.
    quarantined: bool = False

    @property
    def is_strings(self) -> bool:
        return self.lengths is not None

    def _require_data(self):
        """The fail-loudly half of the salvage placeholder contract:
        touching a quarantined column's data raises, it never yields a
        None-shaped array that could be stored downstream."""
        if self.quarantined:
            raise ValueError(
                f"column {'.'.join(self.descriptor.path)} was quarantined "
                "by salvage for this row group (see the reader's "
                "salvage_report); its data does not exist"
            )

    def __dlpack__(self, **kw):
        self._require_data()
        return self.values.__dlpack__(**kw)

    def __dlpack_device__(self):
        self._require_data()
        return self.values.__dlpack_device__()

    def _host(self, arr):
        return np.asarray(arr) if arr is not None else None

    def to_numpy(self) -> np.ndarray:
        """``values`` on host as NumPy (bit-form DOUBLE → float64)."""
        self._require_data()
        v = np.asarray(self.values)
        if self.f64_bits and v.dtype == np.int64:
            v = v.view(np.float64)
        return v

    def bytes_list(self) -> list:
        """Strings as a list of ``bytes`` (both engine layouts)."""
        self._require_data()
        if not self.is_strings:
            raise ValueError("bytes_list() is for string columns")
        if isinstance(self.values, ByteArrayColumn):
            return self.values.to_list()
        rows = self._host(self.values)
        lens = self._host(self.lengths)
        buf = rows.tobytes()
        ml = rows.shape[1] if rows.ndim == 2 else 0
        return [
            buf[i * ml : i * ml + int(ln)] for i, ln in enumerate(lens)
        ]

    def to_arrow(self):
        """This column as a ``pyarrow`` array.

        Host primitives wrap the NumPy buffer zero-copy (the validity
        bitmap, when present, is built); host strings become
        ``large_binary`` over the existing offsets+data buffers
        (zero-copy); device arrays are fetched to host first; FLBA/INT96
        byte rows become ``fixed_size_binary``.
        """
        import pyarrow as pa

        self._require_data()
        if self.rep_levels is not None:
            raise ValueError(
                "to_arrow() serves flat columns; assemble repeated "
                "leaves via assemble_nested()/DeviceColumn.assemble()"
            )
        mask = self._host(self.mask)

        def validity_and_nulls():
            # built only for the from_buffers branches; pa.array(mask=)
            # builds its own bitmap on the common primitive path
            if mask is None:
                return None, 0
            return (
                pa.py_buffer(np.packbits(~mask, bitorder="little")),
                int(mask.sum()),
            )

        if self.is_strings:
            validity, null_count = validity_and_nulls()
            if isinstance(self.values, ByteArrayColumn):
                offsets, data = self.values.offsets, self.values.data
            else:
                rows = self._host(self.values)
                lens = self._host(self.lengths).astype(np.int64)
                offsets = np.zeros(len(lens) + 1, dtype=np.int64)
                np.cumsum(lens, out=offsets[1:])
                if len(lens) and rows.size:
                    ml = rows.shape[1]
                    lane = np.arange(ml)[None, :]
                    data = rows[lane < lens[:, None]]
                else:
                    data = np.zeros(0, np.uint8)
            return pa.LargeBinaryArray.from_buffers(
                pa.large_binary(), len(offsets) - 1,
                [validity, pa.py_buffer(offsets), pa.py_buffer(data)],
                null_count=null_count,
            )
        vals = self.to_numpy()
        if vals.ndim == 2:  # FLBA / INT96 byte rows
            validity, null_count = validity_and_nulls()
            width = vals.shape[1]
            flat = np.ascontiguousarray(vals, dtype=np.uint8)
            return pa.FixedSizeBinaryArray.from_buffers(
                pa.binary(width), len(vals),
                [validity, pa.py_buffer(flat)], null_count=null_count,
            )
        return pa.array(vals, mask=mask)


def take_rows(values, def_levels, max_definition_level: int,
              row_idx: np.ndarray):
    """Gather whole ROWS of one flat column by row index: returns
    ``(new_values, new_def_levels)``.  ``values`` holds non-null values
    only (the ColumnBatch/ColumnData layout) — present rows map through
    the definition levels to value positions.  The one definition of
    the null-aware row gather shared by the host pushdown compaction
    (``scan/executor.py``) and the compactor's within-group sort
    (``write/compactor.py``)."""
    if def_levels is not None:
        new_dl = def_levels[row_idx]
        present = def_levels == max_definition_level
        vidx = np.cumsum(present) - 1
        sel = row_idx[present[row_idx]]
        take = vidx[sel]
    else:
        new_dl = None
        take = row_idx
    vals = (
        values.take(take)
        if isinstance(values, ByteArrayColumn)
        else np.asarray(values)[take]
    )
    return vals, new_dl


def batch_to_arrow(columns: List["BatchColumn"]):
    """A list of flat ``BatchColumn``s (one row group) as a
    ``pyarrow.RecordBatch`` in the given column order."""
    import pyarrow as pa

    return pa.RecordBatch.from_arrays(
        [c.to_arrow() for c in columns],
        names=[".".join(c.descriptor.path) for c in columns],
    )
