"""Partial aggregates — the host half of aggregate pushdown.

An :class:`Aggregate` names what to compute — ``count``/``sum``/``min``/
``max`` per column, optionally grouped by one (dictionary-encoded)
column.  Each row group produces one :class:`AggPartial` — a tiny
per-group state (O(groups) values, not O(rows)) — and
:meth:`AggPartial.combine` folds partials across row groups and files
into the final answer.  The partials are face-agnostic: the device
compute tail (``tpu.compute``), the host scan leg, and the serving
lookup face all emit the same state, so a scan can mix device-computed
and host-fallback groups freely.

Semantics match ``pyarrow.compute`` (pinned by the differential suite):

* ``count`` counts non-null values (NaN counts);
* ``sum`` accumulates int32→int64, int64→int64 (wraparound), floats in
  float64 (float32 sums return double, exactly as pyarrow's ``sum``);
  NaN propagates;
* ``min``/``max`` skip NaN; a group with values but only NaN yields
  ``inf``/``-inf`` (pyarrow's ``min_max``); a group with zero non-null
  values yields None;
* with ``group_by``, rows whose group key is null fold into a ``None``
  key group (pyarrow's ``group_by`` null group), and keys that appear
  only in filtered-out rows do not appear at all.

Float sums are order-sensitive in IEEE arithmetic; partials accumulate
in float64 in row order per group, so host and device agree bit-exactly
whenever the data's sums are exactly representable (integers-as-floats
— the differential suite's shape) and to rounding otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

_OPS = ("count", "sum", "min", "max")

# the single-bucket key of an ungrouped aggregate (never a real group
# key: dictionary keys are bytes/numbers/None)
ALL = "__all__"

_ACC_DTYPE = {
    "int32": np.int64,
    "int64": np.int64,
    "float32": np.float64,
    "float64": np.float64,
}


def neutral_min(dtype) -> object:
    dt = np.dtype(dtype)
    return np.inf if dt.kind == "f" else np.iinfo(dt).max


def neutral_max(dtype) -> object:
    dt = np.dtype(dtype)
    return -np.inf if dt.kind == "f" else np.iinfo(dt).min


@dataclass(frozen=True)
class Aggregate:
    """An aggregate request: ``aggs`` is a tuple of ``(column, op)``
    pairs (op in ``count``/``sum``/``min``/``max``), ``group_by``
    optionally names the grouping column.  Hashable, so it can ride jit
    static arguments (part of the fused executable's cache key)."""

    aggs: Tuple[Tuple[str, str], ...]
    group_by: Optional[str] = None

    def __post_init__(self):
        object.__setattr__(
            self, "aggs", tuple((str(c), str(o)) for c, o in self.aggs)
        )
        if not self.aggs:
            raise ValueError("Aggregate needs at least one (column, op)")
        for c, o in self.aggs:
            if o not in _OPS:
                raise ValueError(
                    f"unknown aggregate op {o!r} on {c!r} (use one of "
                    f"{', '.join(_OPS)})"
                )

    def columns(self) -> set:
        out = {c for c, _ in self.aggs}
        if self.group_by is not None:
            out.add(self.group_by)
        return out


class AggPartial:
    """Partial aggregate state of one row group — or a fold of several.

    ``groups`` maps a group key (bytes / number / None for the null
    group; :data:`ALL` when ungrouped) to ``[rows, states]`` where
    ``rows`` counts selected rows and ``states`` holds one
    ``[n_valid, value]`` pair per ``Aggregate.aggs`` entry (``value`` is
    the running sum / min / max in the op's accumulator dtype; neutral
    until a valid value lands)."""

    __slots__ = ("spec", "groups")

    def __init__(self, spec: Aggregate):
        self.spec = spec
        self.groups: Dict[object, list] = {}

    # -- accumulation --------------------------------------------------------

    def _bucket(self, key) -> list:
        b = self.groups.get(key)
        if b is None:
            b = [0, [[0, None] for _ in self.spec.aggs]]
            self.groups[key] = b
        return b

    def add_rows(self, key, rows: int) -> None:
        self._bucket(key)[0] += int(rows)

    def add_state(self, key, agg_index: int, n_valid: int, value) -> None:
        """Fold one op's ``(n_valid, value)`` into the bucket (value in
        accumulator dtype; None when the op is ``count`` or when no
        valid value contributed)."""
        st = self._bucket(key)[1][agg_index]
        st[0] += int(n_valid)
        if value is None:
            return
        op = self.spec.aggs[agg_index][1]
        if st[1] is None:
            st[1] = value
        elif op == "sum":
            st[1] = st[1] + value  # numpy scalar add: wraparound for ints
        elif op == "min":
            st[1] = min(st[1], value)
        elif op == "max":
            st[1] = max(st[1], value)

    # -- the combine protocol ------------------------------------------------

    def combine(self, other: "AggPartial") -> "AggPartial":
        """Fold ``other`` into self (associative; group keys union)."""
        if other.spec != self.spec:
            raise ValueError("cannot combine partials of different specs")
        for key, (rows, states) in other.groups.items():
            self.add_rows(key, rows)
            for i, (nv, val) in enumerate(states):
                self.add_state(key, i, nv, val)
        return self

    @classmethod
    def merge(cls, spec: Aggregate, partials) -> "AggPartial":
        out = cls(spec)
        for p in partials:
            out.combine(p)
        return out

    # -- results -------------------------------------------------------------

    def finalize(self) -> dict:
        """The answer: ungrouped → ``{"col_op": value}``; grouped →
        ``{key: {"col_op": value}}`` (key None = the null group).  Ops
        with zero valid values yield None (count yields 0); sums and
        min/max convert to plain Python scalars."""
        def fin(states) -> dict:
            out = {}
            for (c, o), (nv, val) in zip(self.spec.aggs, states):
                name = f"{c}_{o}"
                if o == "count":
                    out[name] = int(nv)
                elif nv == 0:
                    out[name] = None
                else:
                    out[name] = None if val is None else np.asarray(val).item()
            return out

        if self.spec.group_by is None:
            _, states = self.groups.get(ALL, [0, [[0, None] for _ in self.spec.aggs]])
            return fin(states)
        return {
            key: fin(states)
            for key, (rows, states) in self.groups.items()
            if rows > 0
        }

    @property
    def rows(self) -> int:
        """Selected rows folded into this partial (all groups)."""
        return sum(rows for rows, _ in self.groups.values())


def _valid_state(op: str, vals: np.ndarray, present: np.ndarray):
    """One op's ``(n_valid, value)`` over the present values."""
    pv = vals[present]
    nv = int(pv.size)
    if op == "count":
        return nv, None
    dt = vals.dtype
    if op == "sum":
        acc = _ACC_DTYPE[dt.name]
        return nv, (None if nv == 0 else np.sum(pv.astype(acc), dtype=acc))
    # min/max skip NaN (pyarrow min_max); all-NaN yields the neutral
    if dt.kind == "f":
        pv = pv[~np.isnan(pv)]
    if nv == 0:
        return 0, None
    if pv.size == 0:
        return nv, np.asarray(neutral_min(dt) if op == "min" else neutral_max(dt), dt)
    return nv, (np.min(pv) if op == "min" else np.max(pv))


def host_partial(spec: Aggregate, resolve, n: int,
                 sel: Optional[np.ndarray] = None) -> AggPartial:
    """Compute one row group's :class:`AggPartial` on host.

    ``resolve(name)`` returns ``(values, null_mask)`` — numeric NumPy
    arrays, or object arrays of ``bytes`` for string group keys;
    ``sel`` restricts to the selected rows (a predicate's mask)."""
    out = AggPartial(spec)
    idx = np.arange(n) if sel is None else np.flatnonzero(np.asarray(sel, bool))
    cols = {}
    for c in spec.columns():
        vals, mask = resolve(c)
        vals = np.asarray(vals)
        present = (
            np.ones(n, bool) if mask is None else ~np.asarray(mask, bool)
        )
        cols[c] = (vals[idx], present[idx])
    if spec.group_by is None:
        out.add_rows(ALL, idx.size)
        for i, (c, o) in enumerate(spec.aggs):
            vals, present = cols[c]
            nv, val = _valid_state(o, vals, present)
            out.add_state(ALL, i, nv, val)
        return out
    gvals, gpresent = cols[spec.group_by]
    # one bucket per distinct present key, plus the null group
    for key_rows in _group_rows(gvals, gpresent):
        key, rows = key_rows
        out.add_rows(key, rows.size)
        for i, (c, o) in enumerate(spec.aggs):
            vals, present = cols[c]
            nv, val = _valid_state(o, vals[rows], present[rows])
            out.add_state(key, i, nv, val)
    return out


def _group_rows(gvals: np.ndarray, gpresent: np.ndarray):
    """Yield ``(key, row_indices)`` per distinct group key (None = the
    null group), in first-appearance order."""
    null_rows = np.flatnonzero(~gpresent)
    if null_rows.size:
        yield None, null_rows
    live = np.flatnonzero(gpresent)
    if not live.size:
        return
    pv = gvals[live]
    if pv.dtype == object:
        seen: Dict[object, list] = {}
        for i, v in zip(live, pv):
            seen.setdefault(v, []).append(i)
        for key, rows in seen.items():
            yield key, np.asarray(rows)
        return
    uniq, inv = np.unique(pv, return_inverse=True)
    for j, u in enumerate(uniq):
        yield u.item(), live[inv == j]
