"""Query subsystem (docs/query.md): projection expressions over the
fused decode tail, sorted-merge joins over ``sort_by``-compacted
corpora, and persistent secondary indexes built at compaction time.

Three pillars, each a serving-daemon op with per-tenant attribution and
a bench gate (``bench.py query_leg``):

* :mod:`.expr` — ``Expr`` trees compiled into the one-launch decode
  executable as computed output columns (host twin bit-equal).
* :mod:`.join` — memory-bounded streaming merge join of two corpora
  compacted with ``sort_by`` on the join key, resumable via stateless
  fingerprinted tokens.
* :mod:`.index` — key → (file, group, row-span) sidecars emitted by
  ``DatasetCompactor(index_columns=...)``; ``serve.Dataset.lookup``
  consults an installed index before the stats/bloom rungs.
"""

from .expr import (  # noqa: F401
    ComputedColumn,
    Expr,
    as_expr_tree,
    computed_descriptor,
    eval_expr,
    eval_expr_host,
    expr_columns,
    exprs_signature,
    qcol,
    qlit,
    tree_from_json,
    validate_expr,
)
from .index import SecondaryIndex  # noqa: F401
from .join import JoinCursor, sorted_merge_join  # noqa: F401

__all__ = [
    "ComputedColumn",
    "Expr",
    "JoinCursor",
    "SecondaryIndex",
    "as_expr_tree",
    "computed_descriptor",
    "eval_expr",
    "eval_expr_host",
    "expr_columns",
    "exprs_signature",
    "qcol",
    "qlit",
    "sorted_merge_join",
    "tree_from_json",
    "validate_expr",
]
