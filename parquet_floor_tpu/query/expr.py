"""Projection expressions — computed output columns (docs/query.md).

An :class:`Expr` is a small arithmetic / comparison / boolean / cast
tree over column references and literals, built with operators::

    from parquet_floor_tpu.query import qcol, qlit

    e = (qcol("price") * qcol("qty")).cast("float64") / qlit(100.0)

Like ``batch.predicate``, the builder is sugar over a STATIC nested
tuple (:meth:`Expr.tree`) — the one structural form every evaluator
consumes, hashable so it can ride a jit static argument (which is how
an expression becomes part of a fused decode executable's persistent
exec-cache key, ``docs/pushdown.md``).  Node forms:

* ``("col", name)`` / ``("lit", value)`` — value is bool/int/float
* ``("bin", op, a, b)`` — op in ``+ - * / == != < <= > >= & |``
* ``("not", a)`` / ``("isnull", a)`` / ``("cast", dtype, a)``

Semantics (pinned to ``pyarrow.compute`` by the differential suite):

* **nulls**: the result of any arithmetic/comparison/boolean node is
  null where ANY input is null (pyarrow's non-Kleene kernels);
  ``isnull`` is never null.  Null lanes carry a canonical zero in the
  values buffer so host and device legs stay BIT-equal lane for lane.
* **dtypes**: operands promote via NumPy's ``promote_types`` (applied
  explicitly on both legs, so JAX's weaker promotion lattice can never
  fork the result); integer add/sub/mul wrap at the promoted width
  exactly like ``pyarrow.compute``'s unchecked kernels.
* **division**: ``/`` is ALWAYS true division in float64 — never
  pyarrow's integer division and never its divide-by-zero raise; the
  pyarrow equivalent of ``a / b`` is
  ``pc.divide(pc.cast(a, 'float64'), pc.cast(b, 'float64'))``.
* **NaN** follows IEEE through every op on both legs.

The SAME evaluator body (:func:`eval_expr`) runs over NumPy on host
and ``jax.numpy`` inside the fused device launch — bit-equality is by
construction, not by parallel reimplementation.  :meth:`Expr.eval_host`
is the host twin; device shapes the compute tail cannot stage
(strings, index-form dictionaries, lossy DOUBLE) raise
``UnsupportedFeatureError`` at plan time and whole-scan consumers fall
back to this host leg.
"""

from __future__ import annotations

from typing import Set, Tuple

import numpy as np

_ARITH_OPS = ("+", "-", "*", "/")
_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
_BOOL_OPS = ("&", "|")
_BIN_OPS = _ARITH_OPS + _CMP_OPS + _BOOL_OPS
_CAST_DTYPES = ("bool", "int32", "int64", "float32", "float64")


class Expr:
    """One expression node (module docstring).  Build leaves with
    :func:`qcol` / :func:`qlit`, combine with operators, export the
    static tree with :meth:`tree`."""

    __slots__ = ("_t",)

    def __init__(self, t: tuple):
        self._t = t

    def tree(self) -> tuple:
        """The static nested-tuple export (hashable — the module
        docstring's node grammar)."""
        return self._t

    # -- arithmetic ---------------------------------------------------------

    def _bin(self, op: str, other) -> "Expr":
        return Expr(("bin", op, self._t, _as_operand(other)))

    def _rbin(self, op: str, other) -> "Expr":
        return Expr(("bin", op, _as_operand(other), self._t))

    def __add__(self, o) -> "Expr":
        return self._bin("+", o)

    def __radd__(self, o) -> "Expr":
        return self._rbin("+", o)

    def __sub__(self, o) -> "Expr":
        return self._bin("-", o)

    def __rsub__(self, o) -> "Expr":
        return self._rbin("-", o)

    def __mul__(self, o) -> "Expr":
        return self._bin("*", o)

    def __rmul__(self, o) -> "Expr":
        return self._rbin("*", o)

    def __truediv__(self, o) -> "Expr":
        return self._bin("/", o)

    def __rtruediv__(self, o) -> "Expr":
        return self._rbin("/", o)

    # -- comparison / boolean ----------------------------------------------

    def __eq__(self, o) -> "Expr":  # type: ignore[override]
        return self._bin("==", o)

    def __ne__(self, o) -> "Expr":  # type: ignore[override]
        return self._bin("!=", o)

    def __lt__(self, o) -> "Expr":
        return self._bin("<", o)

    def __le__(self, o) -> "Expr":
        return self._bin("<=", o)

    def __gt__(self, o) -> "Expr":
        return self._bin(">", o)

    def __ge__(self, o) -> "Expr":
        return self._bin(">=", o)

    def __and__(self, o) -> "Expr":
        return self._bin("&", o)

    def __or__(self, o) -> "Expr":
        return self._bin("|", o)

    def __invert__(self) -> "Expr":
        return Expr(("not", self._t))

    def cast(self, dtype: str) -> "Expr":
        if dtype not in _CAST_DTYPES:
            raise ValueError(
                f"cast dtype {dtype!r} not in {_CAST_DTYPES}"
            )
        return Expr(("cast", dtype, self._t))

    def is_null(self) -> "Expr":
        return Expr(("isnull", self._t))

    __hash__ = None  # type: ignore[assignment] - builders are not trees

    def __repr__(self):
        return f"Expr({self._t!r})"

    # -- evaluation ---------------------------------------------------------

    def eval_host(self, resolve, n: int):
        """Evaluate on host NumPy: ``resolve(name) -> (values,
        null_mask|None)``, returns ``(values, null_mask|None)`` — the
        bit-equal twin of the fused device tail (module docstring)."""
        return eval_expr_host(self._t, resolve, n)


def qcol(name: str) -> Expr:
    """Column-reference leaf."""
    return Expr(("col", str(name)))


def qlit(value) -> Expr:
    """Literal leaf (bool / int / float)."""
    return Expr(("lit", _check_literal(value)))


def _check_literal(value):
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        if not -(1 << 63) <= value < (1 << 63):
            raise ValueError(f"integer literal {value} exceeds int64")
        return value
    if isinstance(value, float):
        return value
    raise TypeError(
        f"expression literal {value!r} is not a bool/int/float "
        "(string expressions are not supported)"
    )


def _as_operand(o) -> tuple:
    if isinstance(o, Expr):
        return o._t
    return ("lit", _check_literal(o))


def as_expr_tree(e) -> tuple:
    """Normalize an :class:`Expr` or an exported tree to a VALIDATED
    static tree (the one form the compilers consume)."""
    t = e.tree() if isinstance(e, Expr) else e
    validate_expr(t)
    return t


def validate_expr(t) -> None:
    """Structural check of one exported tree; raises ``ValueError`` on
    anything outside the module-docstring grammar (a resume token or
    daemon request carrying a malformed tree must fail loudly here, not
    deep inside a jit trace)."""
    if not isinstance(t, tuple) or not t:
        raise ValueError(f"expression node must be a tuple, got {t!r}")
    kind = t[0]
    if kind == "col":
        if len(t) != 2 or not isinstance(t[1], str) or not t[1]:
            raise ValueError(f"bad column node {t!r}")
        return
    if kind == "lit":
        if len(t) != 2:
            raise ValueError(f"bad literal node {t!r}")
        _check_literal(t[1])
        return
    if kind == "bin":
        if len(t) != 4 or t[1] not in _BIN_OPS:
            raise ValueError(f"bad binary node {t!r}")
        validate_expr(t[2])
        validate_expr(t[3])
        return
    if kind in ("not", "isnull"):
        if len(t) != 2:
            raise ValueError(f"bad {kind} node {t!r}")
        validate_expr(t[1])
        return
    if kind == "cast":
        if len(t) != 3 or t[1] not in _CAST_DTYPES:
            raise ValueError(f"bad cast node {t!r}")
        validate_expr(t[2])
        return
    raise ValueError(f"unknown expression node kind {kind!r}")


def expr_columns(t: tuple) -> Set[str]:
    """The set of column names one tree references."""
    kind = t[0]
    if kind == "col":
        return {t[1]}
    if kind == "lit":
        return set()
    if kind == "bin":
        return expr_columns(t[2]) | expr_columns(t[3])
    return expr_columns(t[-1])


def tree_from_json(obj) -> tuple:
    """Rebuild a validated tree from its JSON round-trip (lists back to
    tuples) — the daemon ``select`` op's wire shape."""
    def conv(x):
        if isinstance(x, list):
            return tuple(conv(i) for i in x)
        return x

    t = conv(obj)
    validate_expr(t)
    return t


# ---------------------------------------------------------------------------
# The ONE evaluator — polymorphic over NumPy and jax.numpy
# ---------------------------------------------------------------------------

def _zero(xp, dtype):
    return xp.zeros((), dtype=dtype)


def _promote(a, b):
    """Explicit NumPy-lattice promotion (module docstring): applied on
    BOTH legs so JAX's weaker promotion can never fork a result."""
    return np.promote_types(
        np.dtype(str(a.dtype)), np.dtype(str(b.dtype))
    )


def _require_numeric(arr, op: str):
    kind = np.dtype(str(arr.dtype)).kind
    if kind not in "iuf":
        raise ValueError(
            f"operator {op!r} needs numeric operands, got dtype "
            f"{arr.dtype} (cast('int64') booleans first)"
        )


def eval_expr(t: tuple, resolve, n: int, xp):
    """Evaluate one tree over ``xp`` (NumPy or jax.numpy):
    ``resolve(name) -> (values, null_mask|None)``; returns ``(values,
    null_mask|None)`` with null lanes zeroed in the values buffer (the
    canonical-lanes rule that keeps both legs bit-equal)."""
    kind = t[0]
    if kind == "col":
        vals, mask = resolve(t[1])
        vals = xp.asarray(vals)
        if mask is not None:
            mask = xp.asarray(mask, dtype=bool)
            vals = xp.where(mask, _zero(xp, vals.dtype), vals)
        return vals, mask
    if kind == "lit":
        v = t[1]
        dt = (
            np.dtype(bool) if isinstance(v, bool)
            else np.dtype(np.int64) if isinstance(v, int)
            else np.dtype(np.float64)
        )
        return xp.full((n,), v, dtype=dt), None
    if kind == "cast":
        vals, mask = eval_expr(t[2], resolve, n, xp)
        out = vals.astype(np.dtype(t[1]))
        if mask is not None:
            out = xp.where(mask, _zero(xp, out.dtype), out)
        return out, mask
    if kind == "isnull":
        _vals, mask = eval_expr(t[1], resolve, n, xp)
        if mask is None:
            return xp.zeros((n,), dtype=bool), None
        return mask, None
    if kind == "not":
        vals, mask = eval_expr(t[1], resolve, n, xp)
        if np.dtype(str(vals.dtype)).kind != "b":
            raise ValueError(
                f"operator '~' needs a boolean operand, got {vals.dtype}"
            )
        out = ~vals
        if mask is not None:
            out = xp.where(mask, False, out)
        return out, mask
    # binary
    _, op, ta, tb = t
    a, ma = eval_expr(ta, resolve, n, xp)
    b, mb = eval_expr(tb, resolve, n, xp)
    if ma is None:
        mask = mb
    elif mb is None:
        mask = ma
    else:
        mask = ma | mb
    if op in _BOOL_OPS:
        if np.dtype(str(a.dtype)).kind != "b" or \
                np.dtype(str(b.dtype)).kind != "b":
            raise ValueError(
                f"operator {op!r} needs boolean operands, got "
                f"{a.dtype} and {b.dtype}"
            )
        out = (a & b) if op == "&" else (a | b)
    elif op == "/":
        _require_numeric(a, op)
        _require_numeric(b, op)
        a = a.astype(np.float64)
        b = b.astype(np.float64)
        if xp is not np:
            # XLA rewrites division by a compile-time constant into a
            # multiply by its reciprocal — one ulp off for any
            # non-power-of-two literal divisor, forking the host twin.
            # The barrier hides the divisor's constness so the device
            # emits a true IEEE divide.
            from jax import lax

            b = lax.optimization_barrier(b)
        out = a / b
    elif op in _ARITH_OPS:
        _require_numeric(a, op)
        _require_numeric(b, op)
        dt = _promote(a, b)
        a = a.astype(dt)
        b = b.astype(dt)
        out = a + b if op == "+" else a - b if op == "-" else a * b
    else:  # comparison
        dt = _promote(a, b)
        from ..batch import predicate as _pred

        out = _pred._cmp_arrays(a.astype(dt), "==", b.astype(dt)) \
            if op == "==" else _pred._cmp_arrays(
                a.astype(dt), op, b.astype(dt))
    if mask is not None:
        out = xp.where(mask, _zero(xp, out.dtype), out)
    return out, mask


def eval_expr_host(t: tuple, resolve, n: int):
    """Host-NumPy evaluation (errstate-quiet: a zero divisor in a null
    lane must produce the same IEEE inf/nan the device leg does, not a
    RuntimeWarning)."""
    with np.errstate(all="ignore"):
        return eval_expr(t, resolve, n, np)


def computed_descriptor(name: str, dtype):
    """A synthetic optional flat :class:`ColumnDescriptor` for one
    computed output column — what the batch faces hand their hydrator
    for expression outputs (``docs/query.md``)."""
    from ..format.parquet_thrift import Type
    from ..format.schema import OPTIONAL, ColumnDescriptor, PrimitiveType

    kind = np.dtype(str(dtype))
    phys = {
        "bool": Type.BOOLEAN,
        "int32": Type.INT32,
        "int64": Type.INT64,
        "float32": Type.FLOAT,
        "float64": Type.DOUBLE,
    }.get(kind.name)
    if phys is None:
        raise ValueError(f"no parquet physical type for dtype {kind}")
    return ColumnDescriptor(
        (name,), PrimitiveType(name, phys, repetition=OPTIONAL), 1, 0
    )


class ComputedColumn:
    """One computed output column as the device scan face delivers it
    (``scan_device_groups`` with ``ScanOptions(project_exprs=)``):
    ``values`` / ``mask`` are row-aligned with the group's delivered
    columns (compact-trimmed under pushdown).  ``mask`` is True at
    nulls, None when the expression can never be null."""

    __slots__ = ("name", "values", "mask")

    def __init__(self, name: str, values, mask=None):
        self.name = name
        self.values = values
        self.mask = mask

    @property
    def descriptor(self):
        """A synthetic optional flat descriptor (the batch faces'
        positional contract needs one per delivered column)."""
        return computed_descriptor(self.name, self.values.dtype)

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.values)

    def __repr__(self):
        return (
            f"ComputedColumn({self.name!r}, dtype={self.values.dtype}, "
            f"n={int(self.values.shape[0])})"
        )


def exprs_signature(exprs) -> Tuple[Tuple[str, tuple], ...]:
    """Normalize a ``(name, Expr|tree)`` sequence into the validated
    static form every face shares — rejects duplicate output names."""
    out = []
    seen = set()
    for name, e in exprs:
        name = str(name)
        if not name:
            raise ValueError("expression output needs a non-empty name")
        if name in seen:
            raise ValueError(
                f"duplicate expression output name {name!r}"
            )
        seen.add(name)
        out.append((name, as_expr_tree(e)))
    return tuple(out)
