"""Persistent secondary indexes — point probes on NON-sort columns.

A compacted corpus answers point probes on its ``sort_by`` column in
one page through the stats/bloom/page-index ladder, because the sort
clusters each key.  Any OTHER column's values are scattered, so every
row group survives the stats rung and a probe decodes the whole corpus.
A :class:`SecondaryIndex` closes that gap: at compaction time
(``CompactOptions(index_columns=...)``) the compactor records, for one
named column, every key's exact ``(file, group, row_start, row_end)``
row spans into a small JSON sidecar (``<column>.index.json`` next to
the output files).  A serving
:class:`~parquet_floor_tpu.serve.lookup.Dataset` keyed on that column
:meth:`~parquet_floor_tpu.serve.lookup.Dataset.install_index`\\ s the
sidecar and consults it BEFORE the stats/bloom rungs:

* a key the index does not list is **proven absent** — the probe skips
  the corpus without reading a data byte (``serve.index_skips``);
* a listed key decodes exactly its recorded row spans through
  ``read_row_group_ranges`` (``serve.index_hits``) — ≤ one data page of
  storage bytes per span for page-sized row groups, which ``bench.py
  query_leg`` asserts from the cache byte counters.

Soundness is fingerprint-gated exactly like the quarantine sidecar
(same ``quarantine.fingerprint`` keying): the sidecar records each
output file's fingerprint at build time, and ``install_index`` refuses
an index whose fingerprints do not match the dataset's actual files —
a stale index must fail loudly, never silently serve wrong spans.

Keys are typed on the wire (JSON object keys are strings): ints,
floats (hex-exact), strings, bytes, bools, each under a distinct tag,
so ``1`` and ``"1"`` index separately, exactly as they compare in a
predicate probe.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from ..quarantine import fingerprint as file_fingerprint

_VERSION = 1
_FINGERPRINT_MODES = ("tail", "content")


def encode_key(v) -> str:
    """Typed string encoding of one index key (module docstring).
    Floats encode via ``float.hex`` so the round-trip is bit-exact;
    bytes as hex.  ``None`` is not indexable (nulls are not keys)."""
    if v is None:
        raise ValueError("null is not an indexable key")
    if isinstance(v, bool):
        return f"?:{int(v)}"
    if isinstance(v, int):
        return f"i:{v}"
    if isinstance(v, float):
        return f"d:{float(v).hex()}"
    if isinstance(v, bytes):
        return f"b:{v.hex()}"
    if isinstance(v, str):
        return f"s:{v}"
    raise ValueError(
        f"unsupported index key type {type(v).__name__} "
        "(int/float/str/bytes/bool)"
    )


class SecondaryIndex:
    """key → row-span sidecar for ONE column of one compacted corpus
    (module docstring).  ``files`` lists the corpus's file basenames in
    corpus order; ``fps[i]`` is ``files[i]``'s fingerprint.  Spans are
    ``[file_index, group_index, row_start, row_end)`` half-open row
    ranges, stored per encoded key in corpus order."""

    def __init__(self, column: str, path: Optional[str] = None,
                 fingerprint: str = "tail"):
        if not column:
            raise ValueError("index column must be named")
        if fingerprint not in _FINGERPRINT_MODES:
            raise ValueError(
                f"unknown fingerprint mode {fingerprint!r} "
                f"(choose from {_FINGERPRINT_MODES})"
            )
        self.column = column
        self.path = os.fspath(path) if path is not None else None
        self.fingerprint = fingerprint
        self._lock = threading.Lock()
        self._files: List[str] = []
        self._fps: List[str] = []
        self._entries: Dict[str, List[list]] = {}

    # -- building ------------------------------------------------------------

    def add_file(self, name: str, fp: str) -> int:
        """Register one corpus file (basename + fingerprint); returns
        its file index for :meth:`add_span`."""
        with self._lock:
            self._files.append(str(name))
            self._fps.append(str(fp))
            return len(self._files) - 1

    def add_span(self, key, file_index: int, group_index: int,
                 row_start: int, row_end: int) -> None:
        """Record that ``key`` occupies rows ``[row_start, row_end)``
        of one row group.  Adjacent spans of the same key merge."""
        if row_end <= row_start:
            raise ValueError(
                f"empty span [{row_start}, {row_end}) for key {key!r}"
            )
        ek = encode_key(key)
        span = [int(file_index), int(group_index),
                int(row_start), int(row_end)]
        with self._lock:
            spans = self._entries.setdefault(ek, [])
            if spans and spans[-1][:2] == span[:2] and \
                    spans[-1][3] == span[2]:
                spans[-1][3] = span[3]
            else:
                spans.append(span)

    # -- persistence ---------------------------------------------------------

    def save(self, path: Optional[str] = None) -> str:
        """Write the sidecar atomically (temp file + rename); returns
        the path written."""
        p = os.fspath(path) if path is not None else self.path
        if p is None:
            raise ValueError("SecondaryIndex has no path; pass one to save()")
        with self._lock:
            payload = json.dumps(
                {"version": _VERSION, "column": self.column,
                 "fingerprint": self.fingerprint,
                 "files": self._files, "fps": self._fps,
                 "entries": self._entries},
                sort_keys=True,
            )
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(payload)
        os.replace(tmp, p)
        self.path = p
        return p

    @classmethod
    def open(cls, path) -> "SecondaryIndex":
        """Load a sidecar; a file that does not parse, carries an
        unknown version, or is structurally malformed raises
        ``ValueError`` loudly — a corrupt index must never quietly
        serve empty (= wrong) probe answers."""
        p = os.fspath(path)
        try:
            with open(p, "rb") as fh:
                data = json.loads(fh.read().decode("utf-8"))
        except (OSError, MemoryError):
            raise
        except Exception as e:
            raise ValueError(f"secondary index {p!r} does not parse: {e}") \
                from e
        if not isinstance(data, dict) or data.get("version") != _VERSION:
            raise ValueError(
                f"secondary index {p!r} has unknown version "
                f"{data.get('version') if isinstance(data, dict) else data!r}"
            )
        column = data.get("column")
        if not column or not isinstance(column, str):
            raise ValueError(f"secondary index {p!r} names no column")
        idx = cls(column, path=p,
                  fingerprint=data.get("fingerprint") or "tail")
        files, fps = data.get("files") or [], data.get("fps") or []
        if len(files) != len(fps):
            raise ValueError(
                f"secondary index {p!r}: {len(files)} files but "
                f"{len(fps)} fingerprints"
            )
        idx._files = [str(f) for f in files]
        idx._fps = [str(f) for f in fps]
        entries = data.get("entries") or {}
        if not isinstance(entries, dict):
            raise ValueError(f"secondary index {p!r}: malformed entries")
        for ek, spans in entries.items():
            for s in spans:
                if len(s) != 4 or not 0 <= int(s[0]) < len(files) or \
                        int(s[3]) <= int(s[2]):
                    raise ValueError(
                        f"secondary index {p!r}: malformed span {s!r} "
                        f"for key {ek!r}"
                    )
        idx._entries = {
            str(ek): [[int(x) for x in s] for s in spans]
            for ek, spans in entries.items()
        }
        return idx

    # -- queries -------------------------------------------------------------

    @property
    def files(self) -> List[str]:
        with self._lock:
            return list(self._files)

    @property
    def file_fingerprints(self) -> List[str]:
        with self._lock:
            return list(self._fps)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def spans_for(self, key) -> List[tuple]:
        """``(file_index, group_index, row_start, row_end)`` spans for
        one key, corpus order; ``[]`` PROVES the key absent from the
        indexed corpus (the index is exhaustive by construction)."""
        try:
            ek = encode_key(key)
        except ValueError:
            return []
        with self._lock:
            return [tuple(s) for s in self._entries.get(ek, [])]

    def verify_file(self, file_index: int, source) -> bool:
        """True when ``source``'s bytes still match the fingerprint
        recorded for ``file_index`` at build time."""
        with self._lock:
            fp = self._fps[file_index]
        return file_fingerprint(source, self.fingerprint) == fp
