"""Sorted-merge join over ``sort_by``-compacted corpora (docs/query.md).

Two serving :class:`~parquet_floor_tpu.serve.lookup.Dataset`\\ s whose
files were produced by ``DatasetCompactor(sort_by=<join key>)`` stream
through a memory-bounded merge: at any moment the join holds ONE decoded
row group per side plus ONE equal-key run of the right stream — never a
hash table, never a spill file.  The merge trusts the corpora's
RECORDED order and verifies it twice:

* **plan time** — every file's row groups must record
  ``sorting_columns`` with the join key as an ascending, nulls-last
  prefix (what the compactor writes for ``sort_by``); anything else is
  a typed refusal (:class:`UnsupportedFeatureError`), never a silently
  wrong join;
* **run time** — each side's key stream is checked monotone as it is
  consumed (the compactor orders rows *within* its output; a corpus
  assembled from files in the wrong order would otherwise merge
  quietly and drop matches).

Semantics are SQL's: ``how="inner"`` emits one output row per matching
(left, right) pair; ``how="left"`` additionally emits unmatched left
rows with the right columns ``None``.  Null join keys never match
(nulls-last ordering puts them at the tail).  Multi-key joins compare
the key tuples element-wise.  A right-side column whose name collides
with a non-key left column is delivered as ``right.<name>``.

:class:`JoinCursor` pages the merge ``page_rows`` at a time and exposes
a stateless JSON resume token (fingerprinted like the range cursor's —
replay against a different dataset pair/key/projection is refused
loudly); the serving daemon's ``join_page`` op rides it, one bounded
page per request.
"""

from __future__ import annotations

import contextlib
from typing import List, Optional, Sequence

from ..errors import UnsupportedFeatureError
from ..utils import trace

_TOKEN_KEYS = frozenset(("lf", "lg", "lr", "rf", "rg", "rr", "ri", "fp"))


def _has_null(key: tuple) -> bool:
    return any(k is None for k in key)


def _key_lt(a: tuple, b: tuple) -> bool:
    """Strict ``a < b`` under the compactor's order: element-wise,
    nulls LAST per element."""
    for x, y in zip(a, b):
        if x is None and y is None:
            continue
        if x is None:
            return False
        if y is None:
            return True
        if x == y:
            continue
        try:
            return bool(x < y)
        except TypeError as e:
            raise UnsupportedFeatureError(
                f"join keys are not mutually ordered: "
                f"{type(x).__name__} vs {type(y).__name__}"
            ) from e
    return False


def _check_sorted(ds, on: Sequence[str], side: str) -> None:
    """Refuse a corpus whose files do not RECORD the join key as an
    ascending nulls-last ``sorting_columns`` prefix — the compactor's
    ``sort_by`` contract the merge depends on."""
    for i in range(len(ds._sources)):
        lf = ds._file(i)
        with lf.lock:
            groups = list(lf.reader.row_groups)
        for gi, rg in enumerate(groups):
            names = []
            for s in rg.sorting_columns or []:
                idx = int(s.column_idx or 0)
                chunks = rg.columns or []
                md = chunks[idx].meta_data if idx < len(chunks) else None
                if md is None or not md.path_in_schema:
                    raise UnsupportedFeatureError(
                        f"{side} corpus file {i} row group {gi}: "
                        f"sorting_columns references column {idx} with no "
                        "metadata — cannot prove sort order"
                    )
                if s.descending or s.nulls_first:
                    raise UnsupportedFeatureError(
                        f"{side} corpus file {i} row group {gi}: join "
                        "requires ascending nulls-last sort order, but "
                        f"column {'.'.join(md.path_in_schema)!r} records "
                        f"descending={bool(s.descending)} "
                        f"nulls_first={bool(s.nulls_first)}"
                    )
                names.append(".".join(md.path_in_schema))
            if tuple(names[:len(on)]) != tuple(on):
                raise UnsupportedFeatureError(
                    f"{side} corpus file {i} row group {gi} is not "
                    f"recorded as sorted by {list(on)}: sorting_columns="
                    f"{names or None}.  sorted-merge join refuses "
                    "unsorted corpora — recompact with "
                    f"DatasetCompactor(..., sort_by={list(on)})"
                )


def _key_cursors(batch, on: Sequence[str]) -> list:
    from ..api.reader import _ColumnCursor

    by_name = {".".join(b.descriptor.path): b for b in batch.columns}
    cursors = []
    for name in on:
        b = by_name.get(name)
        if b is None:
            raise ValueError(f"join key column {name!r} missing from batch")
        if b.descriptor.max_repetition_level > 0:
            raise UnsupportedFeatureError(
                f"join key column {name!r} is repeated; join keys are "
                "flat-only"
            )
        cursors.append(_ColumnCursor(b))
    return cursors


def _corpus_rows(ds, on: Sequence[str], columns, tenant, start):
    """``(file, group, row, key_tuple, row_dict)`` for every row of the
    dataset at or after ``start`` (inclusive), in corpus order — one
    decoded row group held at a time, decode inside the dataset's
    device-time slice exactly like the probe ladder."""
    filter_set = ds._filter_set(columns)
    if filter_set is not None:
        filter_set = filter_set | {c.split(".")[0] for c in on}
    f0, g0, r0 = start if start else (0, 0, 0)
    for i in range(f0, len(ds._sources)):
        lf = ds._file(i)
        gstart = g0 if i == f0 else 0
        for gi in range(gstart, len(lf.reader.row_groups)):
            rstart = r0 if (i == f0 and gi == gstart) else 0
            with ds._device(tenant):
                with lf.lock:
                    batch = lf.reader.read_row_group(gi, filter_set)
            kcur = _key_cursors(batch, on)
            out = ds._out_columns(batch, columns)
            for r in range(rstart, int(batch.num_rows)):
                key = tuple(c.cell(r) for c in kcur)
                yield i, gi, r, key, {nm: c.cell(r) for nm, c in out}


def _schema_names(ds, columns) -> List[str]:
    """Projected FLAT column names straight from the schema — what an
    unmatched-left output row nulls out when the right stream never
    produced a batch to learn names from."""
    lf = ds._file(0)
    with lf.lock:
        descs = list(lf.reader.schema.columns)
    want = columns if columns is not None else ds._columns
    names = []
    for d in descs:
        name = ".".join(d.path)
        if want is not None and d.path[0] not in set(want) \
                and name not in set(want):
            continue
        if d.max_repetition_level > 0:
            raise UnsupportedFeatureError(
                f"join projection includes repeated column {name!r}; "
                "the join face is flat-only"
            )
        names.append(name)
    return names


class JoinCursor:
    """Paged, resumable sorted-merge join of two datasets (module
    docstring).  Acquire-and-close (or ``with``): :meth:`close`
    releases the merge state (and the datasets themselves when
    constructed with ``own_datasets=True``).

    ``cursor`` resumes from a previous cursor's :attr:`token`; the
    token carries a fingerprint of (both corpora's identities, ``on``,
    ``how``, both projections) and a token minted for ANY other
    configuration is rejected with :class:`ValueError` — a resume
    must never silently merge the wrong corpora.
    """

    def __init__(self, left, right, on: Sequence[str], how: str = "inner",
                 left_columns: Optional[Sequence[str]] = None,
                 right_columns: Optional[Sequence[str]] = None,
                 tenant=None, page_rows: int = 256,
                 cursor: Optional[dict] = None,
                 own_datasets: bool = False):
        from ..serve.lookup import config_fingerprint

        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        if page_rows <= 0:
            raise ValueError(f"page_rows must be > 0, got {page_rows}")
        on = tuple(on)
        if not on:
            raise ValueError("join needs at least one key column in on=")
        for ds, side in ((left, "left"), (right, "right")):
            if ds.key_column != on[0]:
                raise ValueError(
                    f"{side} dataset's key_column "
                    f"({ds.key_column!r}) must equal on[0] ({on[0]!r}) — "
                    "the join streams each corpus in its recorded key "
                    "order"
                )
        _check_sorted(left, on, "left")
        _check_sorted(right, on, "right")
        self._left = left
        self._right = right
        self._on = on
        self._how = how
        self._lcols = list(left_columns) if left_columns else None
        self._rcols = list(right_columns) if right_columns else None
        self._tenant = tenant
        self.page_rows = int(page_rows)
        self._own = bool(own_datasets)
        self._fp = config_fingerprint([
            left._identity(), right._identity(), list(on), how,
            self._lcols, self._rcols,
        ])
        if cursor is not None:
            if not isinstance(cursor, dict) or \
                    not _TOKEN_KEYS <= set(cursor):
                raise ValueError(f"malformed join cursor token: {cursor!r}")
            if cursor["fp"] != self._fp:
                raise ValueError(
                    "join cursor token was minted for a different "
                    "corpus pair / key / projection (token fp="
                    f"{cursor['fp']!r}, this join fp={self._fp!r}) — "
                    "refusing to resume"
                )
        self._token = dict(cursor) if cursor is not None else None
        self._exhausted = False
        self._closed = False
        self._gen = self._merge(cursor)

    # -- the merge -----------------------------------------------------------

    def _merge(self, tok):
        skip = int(tok["ri"]) if tok else 0
        lstart = (int(tok["lf"]), int(tok["lg"]), int(tok["lr"])) \
            if tok else None
        rstart = (int(tok["rf"]), int(tok["rg"]), int(tok["rr"])) \
            if tok else None
        lrows = _corpus_rows(self._left, self._on, self._lcols,
                             self._tenant, lstart)
        rit = _corpus_rows(self._right, self._on, self._rcols,
                           self._tenant, rstart)
        state = {
            "pending": next(rit, None),  # lookahead (pos..., key, row)
            "run_key": None,             # current right equal-key run
            "run": [],
            "run_pos": rstart or (0, 0, 0),
            "prev": None,                # right monotonicity watermark
        }
        rnames = None                    # right names, learned lazily

        def check_mono(prev, key, side):
            if prev is not None and _key_lt(key, prev):
                raise UnsupportedFeatureError(
                    f"{side} corpus is not globally sorted by "
                    f"{list(self._on)}: key {key!r} follows {prev!r}.  "
                    "The compactor orders rows within its output — the "
                    "corpus's files must be listed in key order"
                )

        def load_next_run():
            p = state["pending"]
            if p is None:
                state["run_key"], state["run"] = None, []
                return False
            f, g, r, k, row = p
            check_mono(state["prev"], k, "right")
            state["prev"] = k
            state["run_key"], state["run"] = k, [row]
            state["run_pos"] = (f, g, r)
            p = next(rit, None)
            while p is not None and p[3] == k:
                state["run"].append(p[4])
                p = next(rit, None)
            state["pending"] = p
            return True

        def right_names():
            nonlocal rnames
            if rnames is None:
                rnames = (
                    list(state["run"][0])
                    if state["run"]
                    else _schema_names(self._right, self._rcols)
                )
            return rnames

        def outrow(lrow, rrow):
            out = dict(lrow)
            for nm in right_names():
                if nm in self._on:
                    continue
                val = rrow.get(nm) if rrow is not None else None
                out[f"right.{nm}" if nm in lrow else nm] = val
            return out

        prev_l = None
        for fl, gl, rl, lkey, lrow in lrows:
            check_mono(prev_l, lkey, "left")
            prev_l = lkey
            matched = False
            if not _has_null(lkey):
                while True:
                    if state["run_key"] is None:
                        if not load_next_run():
                            break
                    if _key_lt(state["run_key"], lkey):
                        state["run_key"] = None
                        continue
                    break
                if state["run_key"] == lkey and not _has_null(lkey):
                    matched = True
                    for ri, rrow in enumerate(state["run"]):
                        if skip:
                            skip -= 1
                            continue
                        yield ((fl, gl, rl), state["run_pos"], ri,
                               outrow(lrow, rrow))
            if not matched and self._how == "left":
                if skip:
                    skip -= 1
                    continue
                yield ((fl, gl, rl), state["run_pos"], 0,
                       outrow(lrow, None))

    # -- paging --------------------------------------------------------------

    @property
    def token(self) -> Optional[dict]:
        """JSON-safe resume position after the rows delivered so far
        (``None`` once exhausted)."""
        if self._exhausted:
            return None
        if self._token is not None:
            return dict(self._token)
        return {"lf": 0, "lg": 0, "lr": 0, "rf": 0, "rg": 0, "rr": 0,
                "ri": 0, "fp": self._fp}

    @property
    def exhausted(self) -> bool:
        return self._exhausted

    def next_page(self) -> List[dict]:
        """Up to ``page_rows`` more joined rows (``[]`` when done)."""
        if self._closed:
            raise ValueError("JoinCursor is closed")
        rows: List[dict] = []
        ctx = (
            trace.using(self._tenant.tracer)
            if self._tenant is not None else contextlib.nullcontext()
        )
        with ctx, trace.span("query.join",
                             attrs={"how": self._how,
                                    "on": ",".join(self._on)},
                             observe="query.join_seconds"):
            for lpos, rpos, ri, row in self._gen:
                rows.append(row)
                self._token = {
                    "lf": lpos[0], "lg": lpos[1], "lr": lpos[2],
                    "rf": rpos[0], "rg": rpos[1], "rr": rpos[2],
                    "ri": ri + 1, "fp": self._fp,
                }
                if len(rows) >= self.page_rows:
                    break
            else:
                self._exhausted = True
            trace.count("query.join_pages")
            trace.count("query.join_rows", len(rows))
        return rows

    def __iter__(self):
        while True:
            page = self.next_page()
            if not page:
                return
            yield from page

    def close(self) -> None:
        """Release the merge (and the datasets when owned);
        idempotent."""
        if self._closed:
            return
        self._closed = True
        self._gen.close()
        if self._own:
            self._left.close()
            self._right.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def sorted_merge_join(left, right, on: Sequence[str], how: str = "inner",
                      left_columns: Optional[Sequence[str]] = None,
                      right_columns: Optional[Sequence[str]] = None,
                      tenant=None, page_rows: int = 1024):
    """Generator of joined row dicts — the one-shot face over
    :class:`JoinCursor` (which see, for paging/resume)."""
    cur = JoinCursor(left, right, on, how=how,
                     left_columns=left_columns,
                     right_columns=right_columns,
                     tenant=tenant, page_rows=page_rows)
    try:
        while True:
            page = cur.next_page()
            if not page:
                return
            yield from page
    finally:
        cur.close()
