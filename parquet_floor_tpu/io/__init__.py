"""L1: host filesystem sources/sinks (``source``) and the
remote-storage failure domain (``remote`` — ranged GETs with hedging,
circuit breaking, and classified errors; docs/remote.md)."""
