"""Remote-storage source layer (L1-remote): ranged GETs as a FAILURE
DOMAIN, not just a transport.

Production Parquet lives on object stores with real latency, throttling,
and partial failures — the capability the local ``FileSource`` never has
to model.  :class:`RemoteSource` adapts any :class:`RemoteTransport`
(one ranged-GET method) into the package's positional-source protocol
(``read_at``/``read_many``/``size``/``name``/``close``) and owns the
tail-latency and failure machinery every remote deployment needs:

* **parallel per-range fetches** — ``read_many`` fans its ranges across
  an internal pool, so one vectored extent read costs ~one RTT instead
  of one RTT per range;
* **hedged reads** — a range fetch that outlives the hedge delay
  (adaptive: the source's observed p95 latency, clamped to
  ``[hedge_min_delay_s, hedge_max_delay_s]``; or a fixed
  ``hedge_delay_s``) gets a duplicate request; the first response wins,
  the loser is cancelled/abandoned and counted
  (``io.remote.hedges`` / ``io.remote.hedge_wins`` /
  ``io.remote.hedges_cancelled``).  When both fail, the PRIMARY's error
  is raised — error order stays deterministic no matter which request
  failed first;
* **a per-source circuit breaker** — ``breaker_threshold`` consecutive
  non-throttle failures trip it open and requests fail fast
  (:class:`~parquet_floor_tpu.errors.BreakerOpenError`, carrying the
  remaining cooldown as ``retry_after_s``) until the cooldown passes;
  then ONE half-open probe is admitted, and its outcome closes or
  re-opens the breaker.  Throttles never trip it: a throttling store is
  up, just busy;
* **connection-level error classification** folded into the
  ``ParquetError`` taxonomy (``docs/remote.md``): transport ``OSError``s
  are the transient class (the existing ``RetryingSource`` budgets
  retry them unchanged), :class:`RemoteThrottledError` carries the
  store's ``retry_after_s`` (which throttle-aware backoff honors), and
  anything else a transport raises is wrapped as
  :class:`RemoteFatalError` — no retry schedule is ever burned on a
  denied credential.

Retry composition (the scan executor's chain, built by
``scan.executor._source_chain``)::

    PrefetchedSource                 # extent cache
      └─ ParallelRangeReader         # vectored fan-out (per-range tasks)
           └─ RetryingSource         # per-range retry/deadline budgets
                └─ RemoteSource      # hedging + breaker + classification
                     └─ transport    # one ranged GET

``RetryingSource`` retries one RANGE at a time, so wrapping the remote
source directly would serialize a vectored read; the
:class:`ParallelRangeReader` adapter re-introduces the fan-out ABOVE the
retry layer, giving every range its own full retry/deadline budget while
ranges still fetch concurrently.

Everything observability-facing lands in the registered ``io.*`` trace
names (``utils.trace.names``; table in ``docs/observability.md``).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import List, Optional

from ..errors import (
    BreakerOpenError,
    RemoteFatalError,
    RemoteThrottledError,
    RemoteTransientError,
    TruncatedFileError,
)
from ..utils import trace
from .source import RetryingSource


class RemoteTransport:
    """The minimal contract a remote backend implements — ONE ranged GET
    plus identity.  Documentation-only base (no registration needed):

    * ``get_range(offset, length) -> bytes``: exactly ``length`` bytes at
      ``offset``, or raise.  Transient failures raise ``OSError`` (or
      :class:`RemoteTransientError`); back-pressure raises
      :class:`RemoteThrottledError` (ideally with ``retry_after_s``);
      anything else is treated as fatal.  Called from multiple threads.
    * ``size`` (int), ``name`` (str), optional ``close()``.

    The in-tree implementation is the seeded
    ``testing.SimulatedRemoteSource`` transport; an S3/GCS/HTTP transport
    is one ranged-GET call behind this surface.
    """

    size: int = 0
    name: str = "<remote>"

    def get_range(self, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass


class LatencyStats:
    """Thread-safe reservoir of recent per-request latencies — the
    adaptive hedge delay reads its p95.  (The latency-adaptive prefetch
    controller keeps its OWN per-extent-load EWMA in
    ``scan.executor._AdaptiveController``; its inputs are whole extent
    loads, not single requests.)  Bounded (ring of ``cap`` samples) so
    a long scan tracks the CURRENT tail, not the whole history."""

    def __init__(self, cap: int = 128):
        self._cap = int(cap)
        self._lock = threading.Lock()
        self._ring: List[float] = []
        self._sizes: List[int] = []   # bytes per sample (0 = unsized)
        self._pos = 0
        self.count = 0

    def observe(self, seconds: float, nbytes: int = 0) -> None:
        s = float(seconds)
        b = int(nbytes)
        with self._lock:
            self.count += 1
            if len(self._ring) < self._cap:
                self._ring.append(s)
                self._sizes.append(b)
            else:
                self._ring[self._pos] = s
                self._sizes[self._pos] = b
                self._pos = (self._pos + 1) % self._cap

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._ring:
                return None
            data = sorted(self._ring)
        i = min(len(data) - 1, max(0, int(q * len(data))))
        return data[i]

    def p95(self) -> Optional[float]:
        return self.quantile(0.95)

    def mean_size(self) -> Optional[float]:
        """Mean bytes of the SIZED samples in the window, or None."""
        with self._lock:
            sized = [b for b in self._sizes if b > 0]
        if not sized:
            return None
        return sum(sized) / len(sized)

    def bandwidth_Bps(self) -> Optional[float]:
        """Observed transfer rate over the sized samples (total bytes /
        total seconds), or None.  Includes per-request overhead, so it
        UNDER-estimates the raw link — which over-estimates the extra
        transfer time a larger request implies: the conservative
        direction for widening a hedge delay."""
        with self._lock:
            pairs = [(s, b) for s, b in zip(self._ring, self._sizes)
                     if b > 0]
        tot_s = sum(s for s, _ in pairs)
        tot_b = sum(b for _, b in pairs)
        if tot_b <= 0 or tot_s <= 0:
            return None
        return tot_b / tot_s


class CircuitBreaker:
    """Per-source fail-fast guard (module docstring).  Thread-safe; the
    clock is injectable for tests.  ``check()`` raises
    :class:`BreakerOpenError` while open; ``on_success``/``on_failure``
    report request outcomes (throttles must NOT be reported as
    failures — the caller classifies first)."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 name: str = "<remote>", clock=time.monotonic):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"breaker cooldown must be > 0, got {cooldown_s}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0          # consecutive, since last success
        self._opened_at: Optional[float] = None
        self._probing = False       # a half-open probe is in flight
        self._probe_started: Optional[float] = None

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._probing:
                return "half_open"
            return "open"

    def check(self) -> None:
        """Admission control, called before each request.  While open:
        fail fast with the remaining cooldown as ``retry_after_s``.
        After the cooldown: admit exactly ONE half-open probe; everyone
        else keeps failing fast until the probe resolves.  A probe that
        never resolves (its future was cancelled before running, its
        outcome was neither success nor a countable failure and the
        release was missed) is RECLAIMED after one further cooldown —
        a lost probe must not wedge the breaker open forever."""
        with self._lock:
            if self._opened_at is None:
                return
            now = self._clock()
            remaining = self._opened_at + self.cooldown_s - now
            if remaining <= 0 and (
                not self._probing
                or (self._probe_started is not None
                    and now - self._probe_started > self.cooldown_s)
            ):
                self._probing = True  # this caller is the (new) probe
                self._probe_started = now
                return
            retry_after = max(remaining, 0.0) or self.cooldown_s
        trace.count("io.remote.breaker_fast_fails")
        raise BreakerOpenError(
            f"circuit breaker open for {self.name}: "
            f"{self.threshold} consecutive failures; "
            f"retry in {retry_after:.3f}s",
            retry_after_s=retry_after, path=self.name,
        )

    def on_success(self) -> None:
        with self._lock:
            was_open = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
            self._probe_started = None
        if was_open:
            trace.decision("io.breaker", {
                "path": self.name, "state": "closed",
                "via": "half_open_probe",
            })

    def on_bypass(self) -> None:
        """The request resolved without judging the endpoint (e.g. a
        throttle: the store is up but refused the work).  Releases a
        half-open probe WITHOUT closing or re-opening, so the next
        admitted request becomes a fresh probe instead of the breaker
        wedging on a probe that never got an answer."""
        with self._lock:
            self._probing = False
            self._probe_started = None

    def on_failure(self) -> None:
        with self._lock:
            self._failures += 1
            failures = self._failures  # snapshot for the unlocked trace
            if self._probing:
                # the half-open probe failed: re-open for a fresh cooldown
                self._opened_at = self._clock()
                self._probing = False
                self._probe_started = None
                reopened = True
                tripped = False
            elif self._opened_at is None and self._failures >= self.threshold:
                self._opened_at = self._clock()
                tripped = True
                reopened = False
            else:
                return
        if tripped:
            trace.count("io.remote.breaker_trips")
            trace.decision("io.breaker", {
                "path": self.name, "state": "open",
                "consecutive_failures": failures,
                "cooldown_s": self.cooldown_s,
            })
            trace.flight_fire("breaker_trip", {
                "path": self.name,
                "consecutive_failures": failures,
                "cooldown_s": self.cooldown_s,
            })
        elif reopened:
            trace.decision("io.breaker", {
                "path": self.name, "state": "open", "via": "probe_failed",
                "cooldown_s": self.cooldown_s,
            })


class RemoteSource:
    """Positional source over a :class:`RemoteTransport` (module
    docstring: parallel ranged GETs, hedging, circuit breaker, error
    classification).

    Thread-safe like every source in :mod:`parquet_floor_tpu.io`;
    ``close()`` must not race in-flight reads (the usual quiesce
    contract).  ``fetch_threads`` bounds concurrent transport requests
    issued by THIS source (vectored fan-out and hedges share the pool).

    ``hedge_delay_s=None`` (default) is ADAPTIVE: hedge when a request
    outlives the source's observed p95 latency (clamped to
    ``[hedge_min_delay_s, hedge_max_delay_s]``), widened per request by
    the extra transfer time its byte size implies over the sampled mean
    (:meth:`hedge_delay`) — a large fetch is not "slow" just for being
    big; hedging stays off until ``hedge_min_samples`` latencies are on
    record — there is no tail to estimate from cold.  ``hedge=False``
    disables hedging entirely.

    ``range_deadline_s`` bounds ONE range fetch including its hedge:
    crossing it raises :class:`RemoteTransientError` (retryable above,
    counted ``io.remote.deadlines``) and abandons the in-flight
    requests.
    """

    def __init__(self, transport, *, fetch_threads: int = 8,
                 hedge: bool = True,
                 hedge_delay_s: Optional[float] = None,
                 hedge_min_delay_s: float = 0.01,
                 hedge_max_delay_s: float = 2.0,
                 hedge_min_samples: int = 8,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 1.0,
                 range_deadline_s: Optional[float] = None,
                 clock=time.monotonic):
        if fetch_threads < 1:
            raise ValueError(f"fetch_threads must be >= 1, got {fetch_threads}")
        if hedge_delay_s is not None and hedge_delay_s <= 0:
            raise ValueError(
                f"hedge_delay_s must be > 0 (or None = adaptive), "
                f"got {hedge_delay_s}"
            )
        if range_deadline_s is not None and range_deadline_s <= 0:
            raise ValueError(
                f"range_deadline_s must be > 0 (or None), got {range_deadline_s}"
            )
        self._transport = transport
        self._clock = clock
        self._hedge = bool(hedge)
        self._hedge_delay_s = hedge_delay_s
        self._hedge_min = float(hedge_min_delay_s)
        self._hedge_max = float(hedge_max_delay_s)
        self._hedge_min_samples = int(hedge_min_samples)
        self._range_deadline_s = range_deadline_s
        self.latency = LatencyStats()
        self.breaker = CircuitBreaker(
            breaker_threshold, breaker_cooldown_s,
            name=getattr(transport, "name", "<remote>"), clock=clock,
        )
        self._pool = ThreadPoolExecutor(
            max_workers=int(fetch_threads), thread_name_prefix="pftpu-remote"
        )
        self._closed = False

    # a structural marker the scan executor's chain builder keys on —
    # "my read_many is already parallel; put retries per-range above me"
    parallel_read_many = True

    @property
    def name(self) -> str:
        return getattr(self._transport, "name", "<remote>")

    @property
    def size(self) -> int:
        return int(self._transport.size)

    def hedge_delay(self, length: Optional[int] = None) -> Optional[float]:
        """The CURRENT hedge delay in seconds: the fixed configuration,
        or the adaptive p95-based one; None while hedging is off (or the
        adaptive estimator has too few samples).

        With ``length``, the adaptive delay is BYTE-SIZE-INFORMED: the
        p95 is widened by the extra transfer time the requested size
        implies beyond the sampled mean (at the window's observed
        bytes/s), so a 16 MiB fetch does not hedge on a p95 learned
        from 64 KiB footer reads — a large read that is merely *big* is
        not slow, and duplicating it doubles the most expensive
        requests exactly when they are healthy."""
        if not self._hedge:
            return None
        if self._hedge_delay_s is not None:
            return self._hedge_delay_s
        if self.latency.count < self._hedge_min_samples:
            return None
        p95 = self.latency.p95()
        if p95 is None:
            return None
        extra = 0.0
        if length is not None:
            mean_size = self.latency.mean_size()
            bw = self.latency.bandwidth_Bps()
            if mean_size is not None and bw is not None and bw > 0:
                extra = max(0.0, float(length) - mean_size) / bw
        return min(self._hedge_max, max(self._hedge_min, p95 + extra))

    # -- one physical request ------------------------------------------------

    def _request(self, offset: int, length: int):
        """One transport GET, classified + fed to the breaker and the
        latency reservoir.  Runs on the pool; hedged duplicates run this
        too, so EVERY physical outcome reaches the breaker — a late
        loser that finds the endpoint dead still counts."""
        t0 = self._clock()
        try:
            data = self._transport.get_range(offset, length)
        except BaseException as e:
            err = self._classified(e, offset, length)
            if err is e:
                raise
            raise err from e
        if len(data) != length:
            # a transport that returns a truncated body without raising
            # (dropped connection mid-stream) is a WIRE fault, not a
            # fact about the bytes: classify transient so the retry
            # budgets re-fetch it — mis-framed short bytes reaching the
            # page parser would read as corruption and let salvage
            # quarantine healthy data
            self.breaker.on_failure()
            trace.count("io.remote.faults")
            raise RemoteTransientError(
                f"short remote read: wanted {length} bytes at {offset}, "
                f"transport returned {len(data)}",
                path=self.name, offset=offset,
            )
        self.breaker.on_success()
        self.latency.observe(self._clock() - t0, length)
        trace.count("io.remote.requests")
        trace.count("io.remote.bytes", length)
        return data

    def _classified(self, e: BaseException, offset: int, length: int):
        """Map one transport failure into the taxonomy (module
        docstring) and report it to the breaker.  Returns the exception
        to raise."""
        if isinstance(e, RemoteThrottledError):
            trace.count("io.remote.throttles")
            self.breaker.on_bypass()  # the store answered; release a probe
            return e  # back-pressure: the store is up — never trips
        if isinstance(e, (EOFError, TruncatedFileError)):
            # a deterministic fact about the BYTES, not the wire — and
            # the endpoint demonstrably responded, which is what a
            # half-open probe was asking
            self.breaker.on_success()
            return e
        if isinstance(e, RemoteFatalError):
            self.breaker.on_failure()
            return e
        if isinstance(e, (OSError, TimeoutError)):
            trace.count("io.remote.faults")
            self.breaker.on_failure()
            return e  # the transient class; retry layers see OSError
        if isinstance(e, (KeyboardInterrupt, SystemExit, MemoryError)):
            return e  # environmental / control flow: never reclassified
        self.breaker.on_failure()
        return RemoteFatalError(
            f"fatal transport error reading [{offset}, {offset + length}): "
            f"{e!r}",
            path=self.name, offset=offset,
        )

    # -- hedged range fetch --------------------------------------------------

    def _fetch(self, offset: int, length: int) -> memoryview:
        t_start = self._clock()
        deadline = (
            None if self._range_deadline_s is None
            else t_start + self._range_deadline_s
        )
        self.breaker.check()  # may fail fast (BreakerOpenError)
        # requests run on the pool: bind them to the submitting tracer
        # scope (contextvars do not cross thread-pool submission); an
        # active trace context rides along the same way so origin
        # fetches land in the distributed timeline
        tracer = trace.current()
        request = self._request
        if trace.current_context() is not None:
            request = trace.carry_context(request)
        with trace.span("io.remote.get", length, attrs={
            "path": self.name, "offset": offset, "length": length,
        }):
            futs = [self._pool.submit(tracer.run, request,
                                      offset, length)]
            hedged = False
            errors: List[Optional[BaseException]] = [None, None]
            while True:
                # harvested failures drop out of the wait set — a failed
                # primary must not make wait() return instantly forever
                # while the hedge is still in flight
                outstanding = [
                    f for i, f in enumerate(futs) if errors[i] is None
                ]
                if not outstanding:
                    # every issued request failed: deterministic error
                    # order — the PRIMARY's failure is the one reported,
                    # no matter which request failed first
                    raise errors[0]
                remaining = (
                    None if deadline is None else deadline - self._clock()
                )
                if remaining is not None and remaining <= 0:
                    break  # deadline crossed with requests still in flight
                hd = None if hedged else self.hedge_delay(length)
                if hd is None:
                    timeout = remaining
                else:
                    timeout = hd if remaining is None else min(hd, remaining)
                done, pending = wait(
                    outstanding, timeout=timeout, return_when=FIRST_COMPLETED
                )
                for f in done:
                    i = futs.index(f)
                    try:
                        data = f.result()
                    except BaseException as e:
                        errors[i] = e
                        continue
                    # first successful response wins; the loser (if any)
                    # is cancelled — or abandoned mid-flight — and counted
                    for other in futs:
                        if other is not f and not other.done():
                            other.cancel()
                            trace.count("io.remote.hedges_cancelled")
                    # range-fetch wall split by OUTCOME: the hedge-won
                    # distribution shows what the duplicate bought
                    if hedged and f is futs[1]:
                        trace.count("io.remote.hedge_wins")
                        trace.observe(
                            "io.remote.get_seconds.hedge",
                            self._clock() - t_start,
                        )
                    else:
                        trace.observe(
                            "io.remote.get_seconds.primary",
                            self._clock() - t_start,
                        )
                    return memoryview(data)
                if not done and pending and not hedged and hd is not None \
                        and self._clock() - t_start >= hd:
                    # the primary REALLY outlived the hedge delay — the
                    # wait may have timed out on the (shorter) deadline
                    # remainder instead, and a fetch about to be
                    # abandoned must not issue a duplicate first
                    hedged = True
                    trace.count("io.remote.hedges")
                    trace.decision("io.hedge", {
                        "path": self.name, "offset": offset,
                        "length": length, "delay_s": round(hd, 6),
                    })
                    futs.append(self._pool.submit(
                        tracer.run, request, offset, length
                    ))
            for i, f in enumerate(futs):
                if not f.done():
                    f.cancel()
                    if i >= 1:
                        # only an abandoned HEDGE counts as a cancelled
                        # hedge — a deadline-bound primary with no
                        # duplicate is not phantom hedge activity
                        trace.count("io.remote.hedges_cancelled")
            trace.count("io.remote.deadlines")
            raise RemoteTransientError(
                f"range fetch [{offset}, {offset + length}) exceeded its "
                f"{self._range_deadline_s}s deadline"
                + (" (hedge in flight)" if hedged else ""),
                path=self.name, offset=offset,
            )

    # -- the positional-source surface ---------------------------------------

    def _check_bounds(self, offset: int, length: int) -> None:
        if offset < 0 or offset + length > self.size:
            raise TruncatedFileError(
                f"read [{offset}, {offset + length}) outside remote object "
                f"of {self.size} bytes",
                path=self.name, offset=offset,
            )

    def read_at(self, offset: int, length: int) -> memoryview:
        self._check_bounds(offset, length)
        if length == 0:
            return memoryview(b"")
        return self._fetch(offset, length)

    def read_many(self, ranges) -> list:
        """Vectored read: every range fetched in PARALLEL through the
        pool (each range is its own hedged request), results in request
        order.  Errors keep range order too: the first-listed failing
        range's error is raised after all fetches settle."""
        ranges = list(ranges)
        for o, n in ranges:
            self._check_bounds(o, n)
        if not ranges:
            return []
        if len(ranges) == 1:
            o, n = ranges[0]
            return [self.read_at(o, n)]
        with trace.span(
            "io.read", sum(n for _, n in ranges),
            attrs={"path": self.name, "ranges": len(ranges),
                   "offset": ranges[0][0]},
        ):
            # each range's _fetch WAITS on pool futures, so the fan-out
            # must not ride the same pool (waiters occupying every
            # worker would deadlock the requests they wait for).
            # Transient threads are fine here: coalescing keeps the
            # range count per vectored read small, and the transport
            # requests below still ride the bounded pool.
            results: list = [None] * len(ranges)
            errors: list = [None] * len(ranges)
            tracer = trace.current()
            fetch = self._fetch
            if trace.current_context() is not None:
                fetch = trace.carry_context(fetch)

            def one(i, o, n):
                try:
                    results[i] = (
                        tracer.run(fetch, o, n) if n
                        else memoryview(b"")
                    )
                except BaseException as e:
                    errors[i] = e

            threads = [
                threading.Thread(
                    target=one, args=(i, o, n), daemon=True,
                    name=f"pftpu-remote-range-{i}",
                )
                for i, (o, n) in enumerate(ranges)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for e in errors:
                if e is not None:
                    raise e
            return results

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._pool.shutdown(wait=True)
        close = getattr(self._transport, "close", None)
        if close is not None:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ParallelRangeReader:
    """Vectored fan-out ABOVE a per-range retry layer (module docstring's
    chain).  ``read_many`` maps each range to ``inner.read_at`` on its
    own worker, so every range keeps its OWN retry/deadline budget
    (``RetryingSource`` semantics) while ranges fetch concurrently.
    Error order is deterministic: all ranges settle, the first-listed
    failure raises.  Single reads pass through untouched."""

    def __init__(self, inner, threads: int = 8):
        if threads < 1:
            raise ValueError(f"threads must be >= 1, got {threads}")
        self._inner = inner
        self._pool = ThreadPoolExecutor(
            max_workers=int(threads), thread_name_prefix="pftpu-ranges"
        )

    @property
    def name(self) -> str:
        return self._inner.name

    @property
    def size(self) -> int:
        return self._inner.size

    def read_at(self, offset: int, length: int) -> memoryview:
        return self._inner.read_at(offset, length)

    def read_many(self, ranges) -> list:
        ranges = list(ranges)
        if len(ranges) <= 1:
            return [self._inner.read_at(o, n) for o, n in ranges]
        # bind workers to the submitting tracer scope, like every other
        # pool in the package (contextvars do not cross thread spawns);
        # the active trace context rides along too
        tracer = trace.current()
        read = self._inner.read_at
        if trace.current_context() is not None:
            read = trace.carry_context(read)
        futs = [
            self._pool.submit(tracer.run, read, o, n)
            for o, n in ranges
        ]
        out: list = []
        first_err: Optional[BaseException] = None
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:
                if first_err is None:
                    first_err = e
                out.append(None)
        if first_err is not None:
            raise first_err
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def compose_retrying(src, retries: int, backoff_s: float = 0.05,
                     deadline_s: Optional[float] = None):
    """THE one spelling of the retry/fan-out composition (module
    docstring's chain), shared by ``ParquetFileReader`` and the scan
    executor's ``_source_chain``: wrap ``src`` in a ``RetryingSource``
    and — when the source's ``read_many`` is parallel
    (``parallel_read_many``) — re-parallelize ABOVE it with a
    :class:`ParallelRangeReader`, each range keeping its own full
    retry/deadline budget.

    Already-composed sources pass through untouched: a
    ``RetryingSource`` OR a ``ParallelRangeReader`` at the top of the
    chain means the caller owns the budgets — wrapping again would
    multiply attempts, compound backoffs, and serialize the vectored
    fan-out behind the outer retry loop."""
    if retries <= 0 or isinstance(src, (RetryingSource,
                                        ParallelRangeReader)):
        return src
    remote = getattr(src, "parallel_read_many", False)
    src = RetryingSource(src, retries, backoff_s, deadline_s=deadline_s)
    return ParallelRangeReader(src) if remote else src
