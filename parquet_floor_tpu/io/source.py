"""Host I/O layer (L1): local file sources/sinks with positional reads.

Replaces the reference's Hadoop ``fs`` shims + ``InputFile``/``OutputFile``
adapters (``ParquetReader.java:233-259``, ``ParquetWriter.java:27-53``).
Unlike the shim ``FSDataInputStream`` — which swallows IOExceptions and
returns -1 (``FSDataInputStream.java:21-29``; SURVEY.md §5 says do NOT copy
that) — errors here propagate loudly.

``FileSource`` memory-maps when possible so column chunks slice zero-copy.

Concurrency contract (the scan executor reads from worker threads):

* ``read_at``/``read_many`` are **thread-safe** on every source in this
  module.  The mmap path slices an immutable view; the file path uses
  positional ``os.pread`` (kernel-level offset, no shared seek cursor);
  only the rare non-``fileno`` stream fallback serializes behind a lock.
* ``close()`` is NOT safe to race with in-flight reads — owners must
  quiesce readers first (the scan executor drains its pool before the
  per-file source closes).  Views returned by the mmap path stay valid
  after ``close()`` only until the last view dies (see ``close``).
* ``RetryingSource`` keeps per-*call* retry budgets: concurrent reads
  never share or double-count attempts, and the ``retried_reads``
  observability counter is lock-protected.
"""

from __future__ import annotations

import io
import mmap
import os
import random
import threading
import time
from typing import BinaryIO, Optional, Union

from ..errors import IoRetryExhaustedError, TruncatedFileError
from ..utils import trace

PathLike = Union[str, os.PathLike]


class FileSource:
    """Random-access input: local path (mmap) or seekable binary stream."""

    def __init__(self, source: Union[PathLike, BinaryIO, bytes, bytearray, memoryview]):
        self._own = False
        self._mm: Optional[mmap.mmap] = None
        self._fh: Optional[BinaryIO] = None
        self._fd: Optional[int] = None  # positional-read descriptor
        self._lock = threading.Lock()
        if isinstance(source, (bytes, bytearray, memoryview)):
            self._buf = memoryview(source)
            self._size = len(self._buf)
            self.name = "<bytes>"
            return
        if isinstance(source, (str, os.PathLike)):
            self._fh = open(source, "rb")
            self._own = True
            self.name = os.fspath(source)
        else:
            self._fh = source
            self.name = getattr(source, "name", "<stream>")
        self._fh.seek(0, io.SEEK_END)
        self._size = self._fh.tell()
        try:
            self._mm = mmap.mmap(self._fh.fileno(), 0, access=mmap.ACCESS_READ)
            self._buf = memoryview(self._mm)
        except (ValueError, OSError, io.UnsupportedOperation, AttributeError):
            self._buf = None  # fall back to positional read
        if self._buf is None:
            # no mmap (pipes? empty files? exotic streams): prefer
            # os.pread on a real descriptor — positional reads share no
            # seek cursor, so executor threads never serialize (or race)
            # on the file position.  Only descriptor-less streams keep
            # the seek+read-under-lock fallback.
            try:
                fd = self._fh.fileno()
                os.pread(fd, 0, 0)
                self._fd = fd
            except (OSError, io.UnsupportedOperation, AttributeError):
                self._fd = None

    @property
    def size(self) -> int:
        return self._size

    def read_at(self, offset: int, length: int) -> memoryview:
        """Positional read (thread-safe); returns exactly ``length`` bytes or
        raises."""
        if offset < 0 or offset + length > self._size:
            raise TruncatedFileError(
                f"read [{offset}, {offset + length}) outside file of {self._size} bytes",
                path=self.name, offset=offset,
            )
        if self._buf is not None:
            return self._buf[offset : offset + length]
        if self._fd is not None:
            # pread never touches the shared seek cursor; loop on short
            # reads (pread may return less than asked near page faults
            # on network filesystems)
            parts = []
            got = 0
            while got < length:
                chunk = os.pread(self._fd, length - got, offset + got)
                if not chunk:
                    break
                parts.append(chunk)
                got += len(chunk)
            data = parts[0] if len(parts) == 1 else b"".join(parts)
        else:
            with self._lock:
                self._fh.seek(offset)
                data = self._fh.read(length)
        if len(data) != length:
            raise TruncatedFileError(
                f"short read: wanted {length}, got {len(data)}",
                path=self.name, offset=offset,
            )
        return memoryview(data)

    def read_many(self, ranges) -> list:
        """Vectored positional read: one ``memoryview`` per ``(offset,
        length)`` in ``ranges``, in the given order (thread-safe, same
        exactness guarantee as :meth:`read_at`).

        The scan planner hands this COALESCED extents in ascending file
        order, so the descriptor path degrades to a near-sequential pread
        train and the mmap path to a handful of zero-copy slices.  Ranges
        are validated before the first byte is read: a request outside the
        file raises without issuing any partial I/O.
        """
        ranges = list(ranges)  # accept one-shot iterables: two passes below
        for offset, length in ranges:
            if offset < 0 or offset + length > self._size:
                raise TruncatedFileError(
                    f"vectored read [{offset}, {offset + length}) outside "
                    f"file of {self._size} bytes",
                    path=self.name, offset=offset,
                )
        if not ranges:
            return []
        # storage-read latency split by source kind: this is the local
        # file leg (io/remote.py observes the remote legs per outcome)
        with trace.span(
            "io.read", sum(n for _, n in ranges),
            attrs={"path": self.name, "ranges": len(ranges),
                   "offset": ranges[0][0]},
            observe="io.read_seconds.file",
        ):
            return [self.read_at(o, n) for o, n in ranges]

    def close(self) -> None:
        if self._mm is not None:
            self._buf = None
            try:
                self._mm.close()
            except BufferError:
                # a caller still holds a view into the map (read_at result
                # or a zero-copy page payload): drop our reference and let
                # the map close when the last view dies, instead of
                # raising here — which would also mask the original error
                # when unwinding out of a `with ParquetFileReader(...)`.
                # Surface the leak so it stays diagnosable: close() no
                # longer guarantees release of the file mapping.  Stay
                # silent while an exception is unwinding, though — under
                # -W error a warning raised here would replace the
                # in-flight error (the hazard the bare pass guarded).
                import sys as _sys

                if _sys.exc_info()[0] is None:
                    import warnings

                    warnings.warn(
                        f"{self!r}.close(): a memoryview into the mmap is "
                        "still alive; the file mapping stays open until "
                        "the last view is garbage-collected",
                        ResourceWarning,
                        stacklevel=2,
                    )
            self._mm = None
        if self._own and self._fh is not None:
            self._fh.close()
            self._fh = None
            # the descriptor number is recycled by the OS the moment the
            # fh closes: a pread on it would silently read a DIFFERENT
            # file — fail loudly like the seek path always did
            self._fd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RetryingSource:
    """Bounded retry-with-backoff over any positional source.

    Retries ONLY ``OSError`` — the transient class (flaky NFS/FUSE mounts,
    interrupted syscalls, object-store hiccups).  ``EOFError``/
    ``TruncatedFileError`` and parse errors are *deterministic* facts about
    the bytes and re-raise immediately: retrying them would turn a corrupt
    file into a hang.  Off by default — enable via
    ``ReaderOptions(io_retries=N)``.

    After ``retries`` failed re-attempts the last error is wrapped in
    :class:`~parquet_floor_tpu.errors.IoRetryExhaustedError` (still an
    ``OSError``) carrying the attempt count and read offset.

    The exponential backoff carries uniform jitter (``jitter`` is the
    fraction of each delay added at random, default 10%) so a fleet of
    readers hitting the same flaky mount does not retry in lockstep.
    Backoff is **throttle-aware**: when the caught error carries a
    ``retry_after_s`` (the remote taxonomy's
    :class:`~parquet_floor_tpu.errors.RemoteThrottledError` /
    :class:`~parquet_floor_tpu.errors.BreakerOpenError`), the next sleep
    is at least that long — retrying into a throttle window (or an open
    circuit breaker) would burn attempts a compliant wait would have
    saved.
    Every read that retry *saved* is surfaced as an ``io.retry`` trace
    decision (and exhaustion as ``io.retry_exhausted``), so production
    serving can watch retry rates without new plumbing.

    ``deadline_s`` bounds the TOTAL wall time of one read call across
    all its attempts and backoff sleeps (None = unbounded): a deep
    retry ladder against a dead mount stops when the next sleep would
    cross the deadline, raising :class:`IoRetryExhaustedError` and
    recording an ``io.retry_deadline_exceeded`` trace decision — serving
    paths get a latency ceiling instead of the full exponential
    schedule.  The budget is per *call*, like the attempt budget.
    """

    def __init__(self, inner, retries: int, backoff_s: float = 0.05,
                 sleep=time.sleep, jitter: float = 0.1, rng=random.random,
                 deadline_s: "float | None" = None, clock=time.monotonic):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if jitter < 0:
            raise ValueError(f"jitter must be >= 0, got {jitter}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 (or None for unbounded), "
                f"got {deadline_s}"
            )
        self._inner = inner
        self._retries = int(retries)
        self._backoff_s = float(backoff_s)
        self._sleep = sleep
        self._jitter = float(jitter)
        self._rng = rng
        self._deadline_s = None if deadline_s is None else float(deadline_s)
        self._clock = clock
        self._stat_lock = threading.Lock()
        self.retried_reads = 0  # observability: how often retry saved a read

    @property
    def name(self) -> str:
        return self._inner.name

    @property
    def size(self) -> int:
        return self._inner.size

    def read_at(self, offset: int, length: int) -> memoryview:
        return self._with_retry(
            lambda: self._inner.read_at(offset, length), offset, length
        )

    def read_many(self, ranges) -> list:
        """Vectored read with the same bounded-retry semantics, applied
        per range: each range gets its own full attempt budget (a flaky
        mount failing range 3 never eats range 7's retries), and ranges
        already read are not re-read when a later one retries."""
        ranges = list(ranges)
        inner_many = getattr(self._inner, "read_many", None)
        if inner_many is None:
            return [self.read_at(o, n) for o, n in ranges]
        out: list = []
        for o, n in ranges:
            out.append(self._with_retry(
                lambda o=o, n=n: inner_many([(o, n)])[0], o, n
            ))
        return out

    def _with_retry(self, read_fn, offset: int, length: int) -> memoryview:
        """One read through the bounded retry loop.  The attempt budget is
        strictly per call — concurrent reads from executor threads never
        share or double-count it (see the module concurrency contract)."""
        last: Optional[OSError] = None
        deadline = (
            None if self._deadline_s is None
            else self._clock() + self._deadline_s
        )
        attempts_made = 0
        for attempt in range(self._retries + 1):
            attempts_made = attempt + 1
            try:
                data = read_fn()
                if attempt:
                    with self._stat_lock:
                        self.retried_reads += 1
                        saved = self.retried_reads
                    # the counter is the durable total (decisions ride a
                    # bounded ring buffer and can evict under load)
                    trace.count("io.retries", attempt)
                    trace.decision("io.retry", {
                        "path": self.name, "offset": offset,
                        "attempts": attempt + 1,
                        "retried_reads": saved,
                    })
                return data
            except (EOFError, TruncatedFileError):
                raise  # deterministic: the bytes are not there
            except OSError as e:
                last = e
                if attempt < self._retries:
                    delay = self._backoff_s * (2 ** attempt)
                    delay *= 1.0 + self._jitter * self._rng()
                    retry_after = getattr(e, "retry_after_s", None)
                    if retry_after is not None:
                        # throttle-aware: the server (or the circuit
                        # breaker) named the earliest useful retry time
                        delay = max(delay, float(retry_after))
                    if deadline is not None and \
                            self._clock() + delay > deadline:
                        # the next sleep would cross the total budget:
                        # stop HERE — a latency ceiling that sleeps past
                        # itself is no ceiling at all
                        trace.count("io.retries", attempt)
                        trace.count("io.retry_exhausted")
                        trace.decision("io.retry_deadline_exceeded", {
                            "path": self.name, "offset": offset,
                            "attempts": attempts_made,
                            "deadline_s": self._deadline_s,
                            "error": str(last),
                        })
                        raise IoRetryExhaustedError(
                            f"read of {length} bytes gave up after "
                            f"{attempts_made} attempt(s): the next retry "
                            f"would cross the {self._deadline_s}s "
                            f"deadline: {last}",
                            attempts=attempts_made, path=self.name,
                            offset=offset,
                        ) from last
                    self._sleep(delay)
        trace.count("io.retries", self._retries)
        trace.count("io.retry_exhausted")
        trace.decision("io.retry_exhausted", {
            "path": self.name, "offset": offset,
            "attempts": self._retries + 1, "error": str(last),
        })
        raise IoRetryExhaustedError(
            f"read of {length} bytes failed after {self._retries + 1} "
            f"attempts: {last}",
            attempts=self._retries + 1, path=self.name, offset=offset,
        ) from last

    def close(self) -> None:
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FileSink:
    """Positioned append-only output over a local path or binary stream."""

    def __init__(self, dest: Union[PathLike, BinaryIO]):
        self._own = False
        if isinstance(dest, (str, os.PathLike)):
            self._fh = open(dest, "wb")
            self._own = True
            self.name = os.fspath(dest)
        else:
            self._fh = dest
            self.name = getattr(dest, "name", "<stream>")
        self._pos = 0

    @property
    def pos(self) -> int:
        return self._pos

    def write(self, data) -> int:
        n = self._fh.write(data)
        if n is None:
            n = len(data)
        self._pos += n
        return n

    def close(self) -> None:
        if self._own:
            self._fh.close()
        else:
            self._fh.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
