"""Low-latency point/range lookups — the one-page read path.

A :class:`Dataset` holds a set of parquet files open behind the shared
buffer cache and answers ``lookup(key)`` / ``range(lo, hi)`` probes by
descending the format's own pruning ladder, cheapest rung first:

1. **footer statistics** — row groups whose chunk min/max prove the key
   absent are skipped without reading a byte
   (``serve.lookup_groups_pruned``);
2. **bloom filters** — for equality probes, a group the stats could not
   rule out is probed against the chunk's split-block Bloom filter (no
   false negatives): a miss skips the group
   (``serve.lookup_bloom_skips``);
3. **page indexes** — ``Predicate.row_ranges`` narrows the surviving
   group to the page row-spans whose ColumnIndex min/max may match, and
   ``read_row_group_ranges`` reads exactly those pages' bytes through
   the OffsetIndex (``serve.lookup_pages_read``);
4. **exact filter** — the decoded (page-sized) batch is filtered to the
   exact matching rows.

Every rung's inputs — footer, page indexes, bloom filters, dictionary
pages — are PINNED in the shared cache's metadata tier at open, so a hot
probe's storage traffic is the candidate data page(s) and nothing else:
**≤ one data page of file bytes per selected column** for a point
lookup with page-sized row groups, which the serving bench asserts from
the cache's byte counters (``bench.py serving_leg``,
``scripts/serving_smoke.py``).

Rows come back as plain dicts (column → API-typed value, the row-stream
conversion rules).  The face is flat-only, like the reference's row
stream: a repeated (nested) column in the projection raises.

Concurrency: probes are thread-safe (per-file locks serialize decode on
one file; different files probe concurrently).  Pass ``tenant=`` to
attribute a probe's counters to a tenant's tracer scope.
Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Sequence

from ..batch.predicate import col
from ..errors import UnsupportedFeatureError
from ..format.file_read import ParquetFileReader, ReaderOptions
from ..io.source import FileSource
from ..utils import trace
from .cache import CachedSource, SharedBufferCache

# pinned-metadata coalesce merges TOUCHING ranges only (page indexes and
# bloom filters sit back-to-back before the footer): any positive gap
# could swallow data pages between two dictionary pages into the pinned
# tier, silently voiding the one-page probe byte proof
_META_GAP = 0


def _source_id(s) -> str:
    """A process-stable identity for one dataset source — what the
    cursor-token fingerprint keys on.  Paths ARE the identity; exotic
    source objects degrade to class name (+ any path/name attribute),
    which still distinguishes datasets built over different files."""
    if isinstance(s, (str, bytes, os.PathLike)):
        return os.fspath(s) if not isinstance(s, bytes) else s.decode(
            "utf-8", "surrogateescape"
        )
    p = getattr(s, "path", None) or getattr(s, "name", None)
    return f"{type(s).__name__}:{p}" if p else type(s).__name__


def config_fingerprint(parts) -> str:
    """12-hex-char digest of a JSON-able config description — stamped
    into resume tokens so a token replayed against a DIFFERENT
    dataset/projection/predicate is refused loudly instead of silently
    paging the wrong data."""
    blob = json.dumps(parts, default=repr, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


class _LookupFile:
    """One open file of the dataset: shared-cache-backed source, its
    reader, the per-file probe lock, and the per-file negative cache
    (keys this file PROVABLY lacks — insertion-ordered dict as LRU)."""

    __slots__ = ("source", "reader", "lock", "neg")

    def __init__(self, source: CachedSource, reader: ParquetFileReader):
        self.source = source
        self.reader = reader
        self.lock = threading.Lock()
        self.neg: Dict[object, bool] = {}


def _metadata_ranges(reader: ParquetFileReader) -> List[tuple]:
    """Byte ranges of everything the probe ladder re-reads: page indexes
    (both kinds), bloom filters, and dictionary pages — the pinned
    metadata tier's working set for one file."""
    ranges: List[tuple] = []
    for rg in reader.row_groups:
        for chunk in rg.columns or []:
            for off, ln in (
                (chunk.offset_index_offset, chunk.offset_index_length),
                (chunk.column_index_offset, chunk.column_index_length),
            ):
                if off is not None and ln:
                    ranges.append((int(off), int(ln)))
            md = chunk.meta_data
            if md is None:
                continue
            if md.bloom_filter_offset is not None and md.bloom_filter_length:
                ranges.append(
                    (int(md.bloom_filter_offset), int(md.bloom_filter_length))
                )
            doff = md.dictionary_page_offset
            if doff and md.data_page_offset and md.data_page_offset > doff:
                ranges.append((int(doff), int(md.data_page_offset - doff)))
    return ranges


class Dataset:
    """Point/range-lookup face over a list of parquet files (module
    docstring).  ``key_column`` names the probe column (a flat top-level
    leaf); ``columns`` optionally fixes the projection every probe
    returns (per-probe ``columns=`` overrides).  ``cache=None`` builds a
    private :class:`SharedBufferCache`; pass the serving context's cache
    to share tiers with the scan tenants.  Files open lazily on first
    probe and stay open (close with :meth:`close` / ``with``).

    ``options`` is the usual :class:`ReaderOptions`; ``salvage`` is
    rejected — quarantine semantics are group-wide and would void the
    one-page byte contract (scan the file with a salvage scanner
    instead)."""

    def __init__(self, sources: Sequence, key_column: str,
                 columns: Optional[Sequence[str]] = None,
                 cache: Optional[SharedBufferCache] = None,
                 options: Optional[ReaderOptions] = None,
                 negative_keys: int = 1024):
        if not key_column:
            raise ValueError("key_column must name a column")
        if negative_keys < 0:
            raise ValueError(
                f"negative_keys must be >= 0, got {negative_keys}"
            )
        if options is not None and options.salvage:
            raise UnsupportedFeatureError(
                "Dataset lookup does not support salvage mode: quarantine "
                "decisions are row-group-wide and a one-page probe cannot "
                "make them (use a salvage DatasetScanner)"
            )
        self._sources = list(sources)
        self.key_column = key_column
        self._columns = list(columns) if columns else None
        self._own_cache = cache is None
        self.cache = cache if cache is not None else SharedBufferCache()
        self._options = options
        self._negative_keys = int(negative_keys)
        self._files: Dict[int, _LookupFile] = {}
        self._open_lock = threading.Lock()
        self._closed = False
        #: installed SecondaryIndex (query/index.py) — consulted by
        #: point lookups BEFORE the stats/bloom rungs
        self._index = None

    def _identity(self) -> list:
        """Process-stable identity of this dataset's configuration —
        the cursor-token fingerprint's input."""
        return [
            [_source_id(s) for s in self._sources],
            self.key_column,
            self._columns,
        ]

    # -- open / pin ----------------------------------------------------------

    def _resolve(self, src) -> CachedSource:
        if callable(src) and not hasattr(src, "read_at"):
            src = src()
        inner = src if hasattr(src, "read_at") else FileSource(src)
        try:
            return CachedSource(inner, self.cache)
        except BaseException:
            inner.close()
            raise

    def _file(self, i: int) -> _LookupFile:
        with self._open_lock:
            if self._closed:
                raise ValueError("Dataset is closed")
            lf = self._files.get(i)
            if lf is not None:
                return lf
        # the open runs OUTSIDE the dataset-wide lock (FL-LOCK002): it
        # is real storage I/O — footer read, page-index/bloom/dict-page
        # pinning — and holding _open_lock through it would stall every
        # OTHER file's first probe behind this file's cold open.  Racing
        # opens of the same index are tolerated instead: both pay the
        # open (the shared cache de-duplicates the storage reads), the
        # loser closes its duplicate below.
        source = self._resolve(self._sources[i])
        try:
            meta = self.cache.get_footer(source.key)
            reader = ParquetFileReader(
                source, options=self._options, metadata=meta
            )
            if meta is None:
                self.cache.put_footer(source.key, reader.metadata)
            self._pin_metadata(source, reader)
        except BaseException:
            source.close()
            raise
        lf = _LookupFile(source, reader)
        with self._open_lock:
            if not self._closed and self._files.get(i) is None:
                self._files[i] = lf
                return lf
            existing = self._files.get(i)
            closed = self._closed
        # lost the race, or the dataset closed underneath the open:
        # release our duplicate (reader.close() closes the source chain)
        reader.close()
        if closed:
            raise ValueError("Dataset is closed")
        return existing

    def _pin_metadata(self, source: CachedSource,
                      reader: ParquetFileReader) -> None:
        """Load + pin the file's probe metadata into the hot tier: the
        footer bytes (tail-declared length), page indexes, bloom
        filters, dictionary pages."""
        from ..scan.plan import coalesce

        size = source.size
        if size >= 12:
            tail = bytes(source.read_at(size - 8, 8))
            flen = int.from_bytes(tail[:4], "little")
            if 0 < flen <= size - 12:
                source.load([(size - 8 - flen, flen + 8)], pinned=True)
        ranges = _metadata_ranges(reader)
        if ranges:
            extents = coalesce(ranges, _META_GAP, 8 << 20)
            source.load([(e.offset, e.length) for e in extents], pinned=True)

    # -- the probe ladder ----------------------------------------------------

    def _filter_set(self, columns) -> Optional[set]:
        cols = columns if columns is not None else self._columns
        if cols is None:
            return None
        return set(cols) | {self.key_column.split(".")[0]}

    def _out_columns(self, batch, columns) -> list:
        """(name, cursor) pairs of the projected output columns, flat
        only, in schema order."""
        from ..api.reader import _ColumnCursor

        want = columns if columns is not None else self._columns
        out = []
        for b in batch.columns:
            desc = b.descriptor
            name = ".".join(desc.path)
            if want is not None and desc.path[0] not in set(want) \
                    and name not in set(want):
                continue
            if desc.max_repetition_level > 0:
                raise UnsupportedFeatureError(
                    f"lookup projection includes repeated column {name!r}; "
                    "the lookup face is flat-only (use the batch stream "
                    "with assemble_nested)"
                )
            out.append((name, _ColumnCursor(b)))
        return out

    def _pages_in(self, reader, rg, covered, filter_set) -> int:
        """Data pages whose rows intersect ``covered``, summed over the
        selected chunks (the probe's page cost, OffsetIndex truth)."""
        from ..format.file_read import page_row_spans, spans_overlap

        n = int(rg.num_rows or 0)
        pages = 0
        for chunk in rg.columns or []:
            md = chunk.meta_data
            if filter_set and md is not None and md.path_in_schema and \
                    md.path_in_schema[0] not in filter_set:
                continue
            oi = reader.read_offset_index(chunk)
            if oi is None or not oi.page_locations:
                pages += 1
                continue
            for _pl, a, b in page_row_spans(oi, n):
                if spans_overlap(a, b, covered):
                    pages += 1
        return pages

    def _device(self, tenant):
        """The device-time WFQ slice for one group's decode: a tenant-
        attributed probe queues for a decode lane in weighted virtual-
        time order (``Tenant.device_session``), so a cache-hot tenant's
        probes cannot monopolize the decode engine.  Tenant-less probes
        run ungated (no serving context to arbitrate)."""
        if tenant is not None and hasattr(tenant, "device_session"):
            return tenant.device_session()
        return contextlib.nullcontext()

    def _neg_check(self, lf: _LookupFile, neg_key) -> bool:
        """True when the per-file negative cache proves ``neg_key``
        absent from this file (an earlier probe descended the ladder
        and found nothing) — the stats/bloom rungs short-circuit."""
        if neg_key is None or not self._negative_keys:
            return False
        with lf.lock:
            if neg_key in lf.neg:
                # touch (dict order is the LRU order)
                del lf.neg[neg_key]
                lf.neg[neg_key] = True
                return True
        return False

    def _neg_record(self, lf: _LookupFile, neg_key) -> None:
        if neg_key is None or not self._negative_keys:
            return
        with lf.lock:
            if neg_key not in lf.neg and \
                    len(lf.neg) >= self._negative_keys:
                lf.neg.pop(next(iter(lf.neg)))
            lf.neg[neg_key] = True

    def _group_rows(self, lf: _LookupFile, gi: int, pred, filter_set,
                    tenant, columns) -> list:
        """ONE row group's descent of the pruning ladder — the shared
        engine behind the probe and cursor faces: footer stats → bloom
        → page-index rungs under the file lock, then the ranged decode
        + exact filter inside a device-time slice (per-group locks so
        a lane wait never head-of-line-blocks other probes of the
        file).  Returns ``[(row_index, row_dict), ...]`` for the
        matching rows (empty when any rung killed the group); the
        batch is probe-local, so the mask/convert tail runs unlocked.
        """
        reader = lf.reader
        with lf.lock:
            rg = reader.row_groups[gi]
            if not pred.may_match(rg):
                trace.count("serve.lookup_groups_pruned")
                return []
            if not pred.may_match_with(reader, rg):
                # stats kept it, the bloom filter killed it
                trace.count("serve.lookup_bloom_skips")
                return []
            rr = pred.row_ranges(reader, gi)
        if not rr:
            # every page's ColumnIndex ruled it out
            trace.count("serve.lookup_groups_pruned")
            return []
        return self._ranged_decode(lf, gi, rr, pred, filter_set, tenant,
                                   columns)

    def _ranged_decode(self, lf: _LookupFile, gi: int, rr, pred,
                       filter_set, tenant, columns) -> list:
        """The decode + exact-filter tail shared by the ladder and the
        secondary-index rung: ranged page read inside a device-time
        slice, then the predicate-mask exact filter (only matching
        rows pay cell conversion)."""
        import numpy as np

        from ..batch.predicate import eval_mask
        from ..scan.executor import _batch_resolver

        reader = lf.reader
        with self._device(tenant):
            with lf.lock:
                rg = reader.row_groups[gi]
                batch, covered = reader.read_row_group_ranges(
                    gi, rr, filter_set
                )
                if not covered:
                    return []
                trace.count(
                    "serve.lookup_pages_read",
                    self._pages_in(reader, rg, covered, filter_set),
                )
            # the exact-filter rung rides the SAME predicate-mask
            # compiler as the pushdown compute tail (one filter
            # semantics)
            sel = eval_mask(pred, _batch_resolver(batch),
                            batch.num_rows)
            hits = np.flatnonzero(sel)
            if not hits.size:
                return []
            cursors = self._out_columns(batch, columns)
            return [
                (int(r), {n: c.cell(int(r)) for n, c in cursors})
                for r in hits
            ]

    def _index_plan(self, key) -> Optional[dict]:
        """The secondary-index rung's plan for one point probe:
        ``{file_index: {group_index: [(r0, r1), ...]}}`` covering every
        row span the key occupies — or None when no index is installed
        (descend the ladder as usual).  An empty dict PROVES the key
        absent everywhere."""
        if self._index is None:
            return None
        plan: dict = {}
        for fi, gi, r0, r1 in self._index.spans_for(key):
            plan.setdefault(int(fi), {}).setdefault(int(gi), []).append(
                (int(r0), int(r1))
            )
        return plan

    def _probe(self, pred, columns, tenant, limit, neg_key=None,
               index_plan=None):
        ctx = (
            trace.using(tenant.tracer)
            if tenant is not None else contextlib.nullcontext()
        )
        out: List[dict] = []
        done = False
        # the span's wall IS the user-visible probe latency: observe=
        # lands it in the tenant's histogram (inside ``ctx``, so a
        # tenant= probe attributes to the tenant's tracer — the SLO
        # monitor's input)
        with ctx, trace.span("serve.lookup",
                             attrs={"key_column": self.key_column},
                             observe="serve.lookup_seconds"):
            trace.count("serve.lookup_probes")
            filter_set = self._filter_set(columns)
            for i in range(len(self._sources)):
                if done:
                    break
                if index_plan is not None and i not in index_plan:
                    # the index PROVES the key absent from this file:
                    # skip it without opening a byte
                    trace.count("serve.index_skips")
                    continue
                lf = self._file(i)
                if index_plan is None and self._neg_check(lf, neg_key):
                    trace.count("serve.negative_hits")
                    continue
                file_rows0 = len(out)
                if index_plan is not None:
                    # the index rung replaces the stats/bloom/page-index
                    # descent: decode exactly the recorded row spans
                    for gi in sorted(index_plan[i]):
                        if limit is not None and len(out) >= limit:
                            done = True
                            break
                        trace.count("serve.index_hits")
                        for _r, row in self._ranged_decode(
                            lf, gi, index_plan[i][gi], pred, filter_set,
                            tenant, columns,
                        ):
                            out.append(row)
                            if limit is not None and len(out) >= limit:
                                break
                    continue
                for gi in range(len(lf.reader.row_groups)):
                    if limit is not None and len(out) >= limit:
                        done = True
                        break
                    for _r, row in self._group_rows(
                        lf, gi, pred, filter_set, tenant, columns
                    ):
                        out.append(row)
                        if limit is not None and len(out) >= limit:
                            break
                if not done and len(out) == file_rows0:
                    # the whole file was descended and yielded nothing:
                    # for an immutable corpus that PROVES the key
                    # absent here — the next probe short-circuits
                    self._neg_record(lf, neg_key)
            if limit is not None:
                out = out[:limit]
            # counted HERE, after any limit stop, so the registered rows
            # counter never under-reports an early-terminated probe
            trace.count("serve.lookup_rows", len(out))
        return out

    # -- public --------------------------------------------------------------

    def lookup(self, key, columns: Optional[Sequence[str]] = None,
               tenant=None, limit: Optional[int] = None) -> List[dict]:
        """Rows whose ``key_column`` equals ``key``, as dicts.  ``limit``
        stops the probe early (a unique-key point read passes
        ``limit=1``).  Repeatedly-probed ABSENT keys short-circuit at
        the stats/bloom rung via the per-file negative cache
        (``serve.negative_hits``) — sized by ``negative_keys``, sound
        for the immutable corpora this face serves.

        With an installed secondary index (:meth:`install_index`) the
        probe consults the index BEFORE the stats/bloom rungs: an
        unlisted key skips every file unread (``serve.index_skips``),
        a listed key decodes exactly its recorded row spans
        (``serve.index_hits``) — ≤ one data page of storage bytes for
        a point probe on a non-sorted column."""
        return self._probe(
            col(self.key_column) == key, columns, tenant, limit,
            neg_key=key, index_plan=self._index_plan(key),
        )

    def install_index(self, index) -> None:
        """Install a :class:`~parquet_floor_tpu.query.index.SecondaryIndex`
        for this dataset's ``key_column``.  Validates loudly: the index
        must name this key column, cover exactly this dataset's files
        IN ORDER, and every recorded file fingerprint must still match
        the file's bytes — a stale or mismatched index must never
        silently serve wrong spans.  Installing (or refreshing) an
        index invalidates every file's negative-lookup cache: entries
        proven absent by the OLD descent must not answer for the new
        index's truth."""
        if index.column != self.key_column:
            raise ValueError(
                f"index is for column {index.column!r}, but this "
                f"dataset's key_column is {self.key_column!r}"
            )
        n_files = len(index.files)
        if n_files != len(self._sources):
            raise ValueError(
                f"index covers {n_files} files, dataset has "
                f"{len(self._sources)} — the index must be built from "
                "exactly this corpus"
            )
        for i in range(n_files):
            lf = self._file(i)
            with lf.lock:
                ok = index.verify_file(i, lf.source)
            if not ok:
                raise ValueError(
                    f"index fingerprint mismatch for file {i} "
                    f"({index.files[i]!r}): the corpus changed since the "
                    "index was built — rebuild the index"
                )
        with self._open_lock:
            if self._closed:
                raise ValueError("Dataset is closed")
            self._index = index
            files = list(self._files.values())
        # negative-cache invalidation rides OUTSIDE _open_lock (per-file
        # locks only): an installed index changes what "proven absent"
        # means, so every cached negative is suspect
        for lf in files:
            with lf.lock:
                lf.neg.clear()
        trace.decision("serve.index", {
            "action": "install", "column": index.column,
            "keys": len(index), "files": n_files,
        })

    def range(self, lo, hi, columns: Optional[Sequence[str]] = None,
              tenant=None, limit: Optional[int] = None) -> List[dict]:
        """Rows with ``lo <= key_column <= hi`` (inclusive both ends),
        as dicts."""
        pred = (col(self.key_column) >= lo) & (col(self.key_column) <= hi)
        return self._probe(pred, columns, tenant, limit)

    def select(self, exprs, predicate=None,
               columns: Optional[Sequence[str]] = None,
               tenant=None, limit: Optional[int] = None) -> List[dict]:
        """Projection-expression query (docs/query.md): every output
        row carries the projected columns PLUS one computed value per
        ``(name, tree)`` in ``exprs`` (the same validated tree shape
        ``ScanOptions.project_exprs`` takes — build with ``qcol`` /
        ``qlit`` and ``as_expr_tree``).  ``predicate`` prunes row
        groups through the stats/bloom rungs and exact-filters rows;
        expressions evaluate on the host leg (``eval_expr_host``),
        bit-equal to the device scan's fused evaluation by the
        canonical-lanes contract.  Computed nulls come back as None."""
        import numpy as np

        from ..batch.predicate import eval_mask, tree, tree_columns
        from ..query.expr import eval_expr_host, expr_columns, \
            exprs_signature
        from ..scan.executor import _batch_resolver

        sig = exprs_signature(exprs)
        need = set()
        for _en, et in sig:
            need |= {c.split(".")[0] for c in expr_columns(et)}
        if predicate is not None:
            need |= {c.split(".")[0]
                     for c in tree_columns(tree(predicate))}
        want = columns if columns is not None else self._columns
        filter_set = None if want is None else set(want) | need
        ctx = (
            trace.using(tenant.tracer)
            if tenant is not None else contextlib.nullcontext()
        )
        out: List[dict] = []
        with ctx, trace.span("serve.select",
                             attrs={"exprs": len(sig)},
                             observe="serve.select_seconds"):
            trace.count("serve.select_probes")
            done = False
            for i in range(len(self._sources)):
                if done:
                    break
                lf = self._file(i)
                reader = lf.reader
                for gi in range(len(reader.row_groups)):
                    if limit is not None and len(out) >= limit:
                        done = True
                        break
                    with lf.lock:
                        rg = reader.row_groups[gi]
                        if predicate is not None:
                            if not predicate.may_match(rg):
                                trace.count("serve.lookup_groups_pruned")
                                continue
                            if not predicate.may_match_with(reader, rg):
                                trace.count("serve.lookup_bloom_skips")
                                continue
                    with self._device(tenant):
                        with lf.lock:
                            batch = reader.read_row_group(gi, filter_set)
                        resolve = _batch_resolver(batch)
                        n = int(batch.num_rows)
                        if predicate is not None:
                            hits = np.flatnonzero(
                                eval_mask(predicate, resolve, n)
                            )
                        else:
                            hits = np.arange(n)
                        if not hits.size:
                            continue
                        cursors = self._out_columns(batch, columns)
                        computed = [
                            (en, eval_expr_host(et, resolve, n))
                            for en, et in sig
                        ]
                        for r in hits:
                            r = int(r)
                            row = {nm: c.cell(r) for nm, c in cursors}
                            for en, (vals, mask) in computed:
                                row[en] = (
                                    None
                                    if mask is not None and bool(mask[r])
                                    else vals[r].item()
                                )
                            out.append(row)
                            if limit is not None and len(out) >= limit:
                                break
            if limit is not None:
                out = out[:limit]
            trace.count("serve.select_rows", len(out))
        return out

    def range_cursor(self, lo, hi,
                     columns: Optional[Sequence[str]] = None,
                     tenant=None, page_rows: int = 256,
                     cursor: Optional[dict] = None) -> "RangeCursor":
        """A bounded-memory streaming face over a (possibly huge)
        ``range()`` result: rows come out in ladder order, at most one
        row group decoded and held at a time, paged ``page_rows`` at a
        time.  ``cursor`` resumes from a previous cursor's
        :attr:`RangeCursor.token` — the token is a plain position dict
        (file, group, row), so it survives JSON and process boundaries
        (the serving daemon's paging protocol rides it)."""
        return RangeCursor(self, lo, hi, columns, tenant, page_rows,
                           cursor)

    def _range_rows(self, pred, columns, tenant, start):
        """Generator behind :class:`RangeCursor`: ``(file_index,
        group_index, row_in_group, row_dict)`` for every matching row
        at or after ``start`` (exclusive of the already-delivered
        ``start['r']``), descending the same pruning ladder as
        :meth:`_probe` one group at a time (`_group_rows` — ONE
        ladder implementation for both faces).  The device slice is
        released before any row is yielded: a paused consumer must
        never park a decode lane."""
        filter_set = self._filter_set(columns)
        f0 = int(start["f"]) if start else 0
        for i in range(f0, len(self._sources)):
            lf = self._file(i)
            g0 = int(start["g"]) if start and i == f0 else 0
            for gi in range(g0, len(lf.reader.row_groups)):
                r0 = (
                    int(start["r"]) + 1
                    if start and i == f0 and gi == g0 else 0
                )
                ctx = (
                    trace.using(tenant.tracer)
                    if tenant is not None else contextlib.nullcontext()
                )
                with ctx:
                    ready = self._group_rows(lf, gi, pred, filter_set,
                                             tenant, columns)
                for r, row in ready:
                    if r >= r0:
                        yield i, gi, r, row

    def aggregate(self, aggregate, predicate=None, tenant=None):
        """Answer an aggregate query over the dataset's files without
        shipping rows anywhere: descends the same pruning ladder a probe
        uses (footer stats, then bloom for equality predicates), decodes
        only the surviving groups' needed columns, and folds per-group
        :class:`~parquet_floor_tpu.batch.aggregate.AggPartial` states —
        the host mirror of the device scan leg's aggregate pushdown
        (docs/pushdown.md).  Returns the combined partial (call
        ``.finalize()``)."""
        from ..batch.aggregate import Aggregate, AggPartial, host_partial
        from ..batch.predicate import eval_mask, tree, tree_columns
        from ..scan.executor import _batch_resolver

        if not isinstance(aggregate, Aggregate):
            raise ValueError(
                "aggregate must be a batch.aggregate.Aggregate"
            )
        need = set(aggregate.columns())
        if predicate is not None:
            need |= tree_columns(tree(predicate))
        filter_set = {c.split(".")[0] for c in need}
        ctx = (
            trace.using(tenant.tracer)
            if tenant is not None else contextlib.nullcontext()
        )
        out = AggPartial(aggregate)
        with ctx, trace.span("serve.aggregate",
                             attrs={"aggs": len(aggregate.aggs)},
                             observe="serve.aggregate_seconds"):
            trace.count("serve.aggregate_probes")
            for i in range(len(self._sources)):
                lf = self._file(i)
                reader = lf.reader
                # the per-file lock is taken PER GROUP, not across the
                # whole query: an aggregate decodes full groups (the
                # longest-running storage work this face does), and
                # holding the lock throughout would head-of-line-block
                # every concurrent probe of the file for seconds —
                # exactly the serving layer's fairness hazard
                for gi in range(len(reader.row_groups)):
                    with lf.lock:
                        rg = reader.row_groups[gi]
                        if predicate is not None:
                            if not predicate.may_match(rg):
                                trace.count("serve.lookup_groups_pruned")
                                continue
                            if not predicate.may_match_with(reader, rg):
                                trace.count("serve.lookup_bloom_skips")
                                continue
                    # one device-time slice per group decode, same as
                    # the probe face: a full-group aggregate is the
                    # HEAVIEST engine work this face does, exactly what
                    # the WFQ device gate exists to interleave
                    with self._device(tenant):
                        with lf.lock:
                            batch = reader.read_row_group(gi, filter_set)
                        resolve = _batch_resolver(batch)
                        n = int(batch.num_rows)
                        sel = (
                            eval_mask(predicate, resolve, n)
                            if predicate is not None else None
                        )
                        out.combine(
                            host_partial(aggregate, resolve, n, sel)
                        )
        return out

    def page_size_bound(self) -> int:
        """The largest compressed data-page size across the dataset's
        OffsetIndexes — the byte ceiling one hot point probe should stay
        under per selected column (benches assert against this)."""
        bound = 0
        for i in range(len(self._sources)):
            lf = self._file(i)
            with lf.lock:
                for rg in lf.reader.row_groups:
                    for chunk in rg.columns or []:
                        oi = lf.reader.read_offset_index(chunk)
                        if oi is None:
                            continue
                        for pl in oi.page_locations or []:
                            bound = max(
                                bound, int(pl.compressed_page_size or 0)
                            )
        return bound

    def close(self) -> None:
        """Close every open reader (and the cache, when privately
        owned); idempotent."""
        with self._open_lock:
            if self._closed:
                return
            self._closed = True
            files = list(self._files.values())
            self._files.clear()
        for lf in files:
            lf.reader.close()
        if self._own_cache:
            self.cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RangeCursor:
    """Streaming, resumable view of one ``Dataset.range`` result
    (created via :meth:`Dataset.range_cursor`; module docstring).

    Memory is bounded by ONE row group's matching rows regardless of
    the range's total size.  :meth:`next_page` returns up to
    ``page_rows`` row dicts (``[]`` once exhausted); :attr:`token` is
    the JSON-safe resume position AFTER the rows delivered so far —
    feed it to ``range_cursor(..., cursor=token)`` (any process, any
    time) to continue exactly where this cursor stopped, each row
    delivered exactly once.  Iterating the cursor pages internally."""

    def __init__(self, ds: Dataset, lo, hi, columns, tenant,
                 page_rows: int, token: Optional[dict]):
        if page_rows <= 0:
            raise ValueError(f"page_rows must be > 0, got {page_rows}")
        # the fingerprint pins the token to THIS dataset + projection +
        # range: a token replayed against anything else is refused
        # loudly instead of silently paging the wrong rows
        self._fp = config_fingerprint([
            ds._identity(),
            list(columns) if columns is not None else None,
            repr(lo), repr(hi),
        ])
        if token is not None:
            if not isinstance(token, dict) or \
                    not {"f", "g", "r", "fp"} <= set(token):
                raise ValueError(f"malformed cursor token: {token!r}")
            if token["fp"] != self._fp:
                raise ValueError(
                    "cursor token was minted for a different dataset/"
                    f"projection/range (token fp={token['fp']!r}, this "
                    f"cursor fp={self._fp!r}) — refusing to resume"
                )
        self.page_rows = int(page_rows)
        self._tenant = tenant
        pred = (col(ds.key_column) >= lo) & (col(ds.key_column) <= hi)
        self._gen = ds._range_rows(pred, columns, tenant, token)
        self._token = dict(token) if token is not None else None
        self._exhausted = False

    @property
    def token(self) -> Optional[dict]:
        """The resume position (``None`` once the range is exhausted —
        nothing left to resume)."""
        if self._exhausted:
            return None
        return dict(self._token) if self._token is not None else {
            "f": 0, "g": 0, "r": -1, "fp": self._fp,
        }

    @property
    def exhausted(self) -> bool:
        return self._exhausted

    def next_page(self) -> List[dict]:
        """Up to ``page_rows`` more rows (``[]`` when done)."""
        rows: List[dict] = []
        for f, g, r, row in self._gen:
            rows.append(row)
            self._token = {"f": f, "g": g, "r": r, "fp": self._fp}
            if len(rows) >= self.page_rows:
                break
        else:
            self._exhausted = True
        ctx = (
            trace.using(self._tenant.tracer)
            if self._tenant is not None else contextlib.nullcontext()
        )
        with ctx:
            trace.count("serve.cursor_pages")
            trace.count("serve.lookup_rows", len(rows))
        return rows

    def __iter__(self):
        while True:
            page = self.next_page()
            if not page:
                return
            yield from page
