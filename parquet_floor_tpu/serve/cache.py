"""Process-wide shared buffer cache — the serving layer's memory plane.

Every scan used to own a private :class:`~parquet_floor_tpu.scan.executor.
PrefetchedSource` extent cache, so N concurrent tenants over the same hot
files paid N× the storage reads and N× the memory.  The
:class:`SharedBufferCache` here is ONE process-wide store with the two
tiers the format itself defines:

* a **metadata tier** (``meta_bytes`` budget) for the byte ranges every
  request re-reads — footers, page indexes (OffsetIndex/ColumnIndex),
  bloom filters, dictionary pages — inserted *pinned* so data-tier churn
  never evicts them (the tier still has its own LRU cap; evictions there
  are counted, never silent);
* a **data tier** (``data_bytes`` budget) — a byte-budgeted LRU of read
  extents (coalesced column-chunk ranges, lookup pages).

:class:`CachedSource` is the drop-in positional-source wrapper that puts
the cache into the existing scan chain: ``PrefetchedSource`` misses (and
loads) consult — and populate — the shared tiers before touching
storage.  Reads are **single-flight**: two tenants requesting the same
range concurrently issue ONE storage read; the followers wait for the
leader's bytes (``serve.singleflight_waits``).

Correctness under eviction: cached payloads are immutable ``bytes``
copies and callers receive ``memoryview``\\ s over them — evicting an
entry drops the cache's reference, while any in-flight borrower keeps
the buffer alive through its own view.  Eviction can therefore never
corrupt a borrowed buffer, only forget it.

Attribution: hit/miss/wait counters land on the AMBIENT tracer — a
tenant's scan (bound to its own :class:`~parquet_floor_tpu.utils.trace.
Tracer` scope) sees exactly its own cache traffic, while
:meth:`SharedBufferCache.stats` keeps the process-global truth for
benches and dashboards.  Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils import trace

_FOOTER_OBJECTS_MAX = 1024  # parsed footers kept (small objects, hot)


class _Entry:
    """One cached byte range of one file."""

    __slots__ = ("start", "end", "data", "pinned")

    def __init__(self, start: int, end: int, data: bytes, pinned: bool):
        self.start = start
        self.end = end
        self.data = data
        self.pinned = pinned


class _Flight:
    """One in-progress storage read (single-flight leader record)."""

    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[bytes] = None
        self.error: Optional[BaseException] = None


class _FileIndex:
    """Per-file sorted range index (the PrefetchedSource shape: entries
    sorted by start, containment served by the predecessor check)."""

    __slots__ = ("starts", "entries")

    def __init__(self):
        self.starts: List[int] = []
        self.entries: List[_Entry] = []

    def locate(self, offset: int, length: int) -> Optional[_Entry]:
        i = bisect.bisect_right(self.starts, offset) - 1
        if i >= 0:
            e = self.entries[i]
            if offset + length <= e.end:
                return e
        return None

    def insert(self, entry: _Entry) -> None:
        i = bisect.bisect_right(self.starts, entry.start)
        self.starts.insert(i, entry.start)
        self.entries.insert(i, entry)

    def remove(self, entry: _Entry) -> None:
        i = bisect.bisect_left(self.starts, entry.start)
        while i < len(self.starts) and self.starts[i] == entry.start:
            if self.entries[i] is entry:
                del self.starts[i]
                del self.entries[i]
                return
            i += 1


def source_key(source) -> tuple:
    """The cache identity of a positional source: ``(name, size)``.
    Two opens of the same path at the same size share entries; a
    rewritten (resized) file gets a fresh key rather than stale bytes.
    (The in-place same-size rewrite blind spot is the quarantine map's
    fingerprint discussion — a serving deployment that rewrites files in
    place should use new file names, as object stores naturally do.)"""
    return (getattr(source, "name", "<source>"), int(source.size))


class SharedBufferCache:
    """Two-tier (pinned metadata / LRU data) shared byte cache with
    single-flight storage reads.  Thread-safe; see module docstring.

    ``data_bytes`` / ``meta_bytes`` are the tier budgets.  The data tier
    evicts least-recently-used entries when over budget
    (``serve.cache_evictions``); the pinned tier evicts only when ITS
    budget overflows (``serve.meta_evictions`` — visible, never silent).

    ``shm`` optionally mounts a cross-process
    :class:`~parquet_floor_tpu.serve.shm_cache.ShmCacheTier` BELOW this
    cache: a lead that misses here consults (and populates) the shared
    segment before touching storage, so the single-flight law holds
    across worker processes, not just threads (docs/serving.md).  The
    caller keeps ownership of the tier (close order: cache, then tier).
    """

    def __init__(self, data_bytes: int = 256 << 20,
                 meta_bytes: int = 64 << 20, shm=None):
        if data_bytes <= 0:
            raise ValueError(f"data_bytes must be > 0, got {data_bytes}")
        if meta_bytes <= 0:
            raise ValueError(f"meta_bytes must be > 0, got {meta_bytes}")
        self.data_bytes = int(data_bytes)
        self.meta_bytes = int(meta_bytes)
        self.shm = shm
        self._lock = threading.Lock()
        self._files: Dict[tuple, _FileIndex] = {}
        # LRU order per tier: dict preserves insertion order; a touch
        # deletes + reinserts (O(1) amortized)
        self._lru_data: Dict[Tuple[tuple, int, int], _Entry] = {}
        self._lru_meta: Dict[Tuple[tuple, int, int], _Entry] = {}
        self._used_data = 0
        self._used_meta = 0
        self._flights: Dict[Tuple[tuple, int, int], _Flight] = {}
        self._footers: Dict[tuple, object] = {}  # parsed ParquetMetadata
        self._closed = False
        # process-global totals (per-tenant attribution rides the
        # ambient tracer; these are the cross-tenant truth)
        self._hits = 0
        self._misses = 0
        self._hit_bytes = 0
        self._miss_bytes = 0
        self._evictions = 0
        self._meta_evictions = 0
        self._singleflight_waits = 0

    # -- bookkeeping (caller holds the lock) --------------------------------

    def _touch(self, key3: Tuple[tuple, int, int], entry: _Entry) -> None:
        lru = self._lru_meta if entry.pinned else self._lru_data
        if key3 in lru:
            del lru[key3]
            lru[key3] = entry

    def _insert_locked(self, key: tuple, offset: int, data: bytes,
                       pinned: bool) -> _Entry:
        idx = self._files.get(key)
        if idx is None:
            idx = self._files[key] = _FileIndex()
        existing = idx.locate(offset, len(data))
        if existing is not None:
            if pinned and not existing.pinned:
                self._promote_locked(key, existing)
            return existing
        entry = _Entry(offset, offset + len(data), data, pinned)
        idx.insert(entry)
        key3 = (key, entry.start, entry.end)
        if pinned:
            self._lru_meta[key3] = entry
            self._used_meta += len(data)
            self._evict_locked(meta=True)
        else:
            self._lru_data[key3] = entry
            self._used_data += len(data)
            self._evict_locked(meta=False)
        return entry

    def _promote_locked(self, key: tuple, entry: _Entry) -> None:
        """Move a data-tier entry to the pinned tier (metadata discovered
        after the bytes were already cached — e.g. the footer tail read
        before the footer parse could classify it)."""
        key3 = (key, entry.start, entry.end)
        if key3 in self._lru_data:
            del self._lru_data[key3]
            self._used_data -= len(entry.data)
        entry.pinned = True
        self._lru_meta[key3] = entry
        self._used_meta += len(entry.data)
        self._evict_locked(meta=True)

    def _evict_locked(self, meta: bool) -> None:
        lru = self._lru_meta if meta else self._lru_data
        cap = self.meta_bytes if meta else self.data_bytes
        used = self._used_meta if meta else self._used_data
        evicted = 0
        while used > cap and lru:
            key3, entry = next(iter(lru.items()))
            del lru[key3]
            idx = self._files.get(key3[0])
            if idx is not None:
                idx.remove(entry)
            used -= len(entry.data)
            evicted += 1
        if meta:
            self._used_meta = used
            self._meta_evictions += evicted
            if evicted:
                trace.count("serve.meta_evictions", evicted)
        else:
            self._used_data = used
            self._evictions += evicted
            if evicted:
                trace.count("serve.cache_evictions", evicted)

    def _record_hit(self, n: int) -> None:
        self._hits += 1
        self._hit_bytes += n
        trace.count("serve.cache_hits")
        trace.count("serve.cache_hit_bytes", n)

    def _record_miss(self, n: int) -> None:
        self._misses += 1
        self._miss_bytes += n
        trace.count("serve.cache_misses")
        trace.count("serve.cache_miss_bytes", n)

    # -- the byte-range face -------------------------------------------------

    def get(self, key: tuple, offset: int, length: int
            ) -> Optional[memoryview]:
        """The cached bytes covering ``[offset, offset + length)`` of
        file ``key``, or None.  A hit touches the entry's LRU slot and
        counts toward the ambient tracer's hit counters."""
        with self._lock:
            idx = self._files.get(key)
            entry = idx.locate(offset, length) if idx is not None else None
            if entry is None:
                return None
            self._touch((key, entry.start, entry.end), entry)
            self._record_hit(length)
            lo = offset - entry.start
            return memoryview(entry.data)[lo : lo + length]

    def put(self, key: tuple, offset: int, data, pinned: bool = False
            ) -> None:
        """Install bytes at ``offset`` of file ``key`` (copied to an
        immutable buffer; a range already covered is not duplicated —
        though a ``pinned=True`` put promotes a covering data-tier
        entry)."""
        with self._lock:
            self._insert_locked(key, int(offset), bytes(data), pinned)

    def fetch(self, key: tuple, offset: int, length: int, read_fn,
              pinned: bool = False) -> memoryview:
        """``get`` or single-flight read-through: on a miss, exactly one
        caller (the leader) runs ``read_fn()`` and installs the bytes;
        concurrent callers for the same range wait for the leader
        (``serve.singleflight_waits``) instead of issuing duplicate
        storage reads.  A failed leader read propagates to every waiter
        and clears the flight, so a retry layer above re-issues cleanly.
        """
        return self.fetch_many(
            key, [(offset, length)],
            lambda ranges: [read_fn()],
            pinned=pinned,
        )[0]

    def fetch_many(self, key: tuple, ranges: Sequence[Tuple[int, int]],
                   read_many_fn, pinned: bool = False) -> list:
        """Vectored :meth:`fetch`: classify every range as hit / flight
        to await / range to lead in ONE lock pass, then issue a single
        vectored ``read_many_fn(miss_ranges)`` for all led ranges (the
        inner source keeps its own fan-out, e.g. the remote parallel
        fetches), install them, and resolve the waiters.  Returns one
        ``memoryview`` per input range, in input order."""
        ranges = [(int(o), int(n)) for o, n in ranges]
        out: list = [None] * len(ranges)
        leads: List[Tuple[int, int, int]] = []       # (pos, offset, length)
        waits: List[Tuple[int, _Flight, int]] = []   # (pos, flight, length)
        with self._lock:
            if self._closed:
                raise ValueError("SharedBufferCache is closed")
            idx = self._files.get(key)
            led_here: Dict[Tuple[int, int], _Flight] = {}
            for pos, (o, n) in enumerate(ranges):
                entry = idx.locate(o, n) if idx is not None else None
                if entry is not None:
                    if pinned and not entry.pinned:
                        self._promote_locked(key, entry)
                    self._touch((key, entry.start, entry.end), entry)
                    self._record_hit(n)
                    lo = o - entry.start
                    out[pos] = memoryview(entry.data)[lo : lo + n]
                    continue
                fkey = (key, o, n)
                fl = self._flights.get(fkey)
                if fl is None:
                    fl = led_here.get((o, n))
                if fl is not None:
                    self._singleflight_waits += 1
                    trace.count("serve.singleflight_waits")
                    waits.append((pos, fl, n))
                    continue
                fl = _Flight()
                self._flights[fkey] = fl
                led_here[(o, n)] = fl
                self._record_miss(n)
                leads.append((pos, o, n))
        if leads:
            lead_ranges = [(o, n) for _, o, n in leads]
            try:
                if self.shm is not None:
                    # the cross-process tier sits between this cache
                    # and storage: shm hits (and waits on another
                    # worker's in-flight read) never reach read_many_fn
                    bufs = self.shm.read_through(
                        key, lead_ranges, read_many_fn, pinned=pinned
                    )
                else:
                    bufs = read_many_fn(lead_ranges)
            except BaseException as e:
                with self._lock:
                    for _, o, n in leads:
                        fl = self._flights.pop((key, o, n), None)
                        if fl is not None:
                            fl.error = e
                            fl.event.set()
                raise
            with self._lock:
                for (pos, o, n), buf in zip(leads, bufs):
                    data = bytes(buf)
                    entry = self._insert_locked(key, o, data, pinned)
                    fl = self._flights.pop((key, o, n), None)
                    if fl is not None:
                        fl.result = data
                        fl.event.set()
                    lo = o - entry.start
                    out[pos] = memoryview(entry.data)[lo : lo + n]
        for pos, fl, n in waits:
            t0 = time.perf_counter()
            fl.event.wait()
            trace.observe(
                "serve.singleflight_wait_seconds",
                time.perf_counter() - t0,
            )
            if fl.error is not None:
                raise fl.error
            out[pos] = memoryview(fl.result)[:n]
        return out

    # -- parsed-footer objects ----------------------------------------------

    def get_footer(self, key: tuple):
        """The parsed ``ParquetMetadata`` cached for ``key``, or None —
        the object half of the metadata tier (byte ranges keep the raw
        tier honest; the parsed object spares the thrift re-parse that
        dominates a warm re-open)."""
        with self._lock:
            meta = self._footers.get(key)
            if meta is not None:  # touch
                del self._footers[key]
                self._footers[key] = meta
            return meta

    def put_footer(self, key: tuple, metadata) -> None:
        with self._lock:
            if key not in self._footers and \
                    len(self._footers) >= _FOOTER_OBJECTS_MAX:
                self._footers.pop(next(iter(self._footers)))
            self._footers[key] = metadata

    # -- maintenance ---------------------------------------------------------

    def invalidate(self, key: tuple) -> None:
        """Forget every entry (both tiers, parsed footer included) of one
        file — the hook for an external "this object changed" signal."""
        with self._lock:
            idx = self._files.pop(key, None)
            self._footers.pop(key, None)
            if idx is None:
                return
            for entry in idx.entries:
                key3 = (key, entry.start, entry.end)
                if entry.pinned:
                    if key3 in self._lru_meta:
                        del self._lru_meta[key3]
                        self._used_meta -= len(entry.data)
                else:
                    if key3 in self._lru_data:
                        del self._lru_data[key3]
                        self._used_data -= len(entry.data)

    def stats(self) -> dict:
        """Process-global snapshot (cross-tenant truth; the per-tenant
        split rides each tenant's tracer counters)."""
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "hit_bytes": self._hit_bytes,
                "miss_bytes": self._miss_bytes,
                "evictions": self._evictions,
                "meta_evictions": self._meta_evictions,
                "singleflight_waits": self._singleflight_waits,
                "data_bytes_used": self._used_data,
                "meta_bytes_used": self._used_meta,
                "files": len(self._files),
                "footers": len(self._footers),
            }

    def close(self) -> None:
        """Drop every buffer and refuse further fetches; idempotent.
        In-flight borrows stay valid (they hold their own views)."""
        with self._lock:
            self._closed = True
            self._files.clear()
            self._lru_data.clear()
            self._lru_meta.clear()
            self._footers.clear()
            self._used_data = self._used_meta = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class CachedSource:
    """Positional source serving reads through a :class:`SharedBufferCache`.

    Drops into the existing chain BELOW the per-scan ``PrefetchedSource``
    and retry layers: a prefetch load (or any reader byte access) that
    misses the scan's private cache consults the shared tiers first and
    populates them on the way back from storage, so the NEXT tenant's
    identical extent is a memory hit.  ``parallel_read_many`` forwards
    from the inner source, keeping the remote fan-out composition
    (``io.remote.compose_retrying``) intact above a cached remote store.

    ``gate`` (a tenant's fair-share handle, ``serve.tenancy``) meters
    actual STORAGE reads — cache hits bypass it entirely, which is the
    point: fair-share arbitrates the scarce resource (storage bandwidth),
    not the shared memory."""

    def __init__(self, inner, cache: SharedBufferCache,
                 key: Optional[tuple] = None, gate=None):
        self._inner = inner
        self._cache = cache
        self.key = key if key is not None else source_key(inner)
        self._gate = gate
        self.parallel_read_many = getattr(inner, "parallel_read_many", False)

    @property
    def name(self) -> str:
        return getattr(self._inner, "name", "<source>")

    @property
    def size(self) -> int:
        return self._inner.size

    def _read_storage(self, ranges) -> list:
        """The one real-storage read path: fair-share gated (when a gate
        is bound), vectored through the inner source."""
        total = sum(n for _, n in ranges)
        if self._gate is not None:
            self._gate.acquire(total)
        try:
            read_many = getattr(self._inner, "read_many", None)
            if read_many is not None:
                return read_many(ranges)
            return [self._inner.read_at(o, n) for o, n in ranges]
        finally:
            if self._gate is not None:
                self._gate.release(total)

    def read_at(self, offset: int, length: int) -> memoryview:
        return self._cache.fetch_many(
            self.key, [(offset, length)], self._read_storage
        )[0]

    def read_many(self, ranges) -> list:
        return self._cache.fetch_many(self.key, list(ranges),
                                      self._read_storage)

    def load(self, ranges, pinned: bool = False) -> int:
        """Ensure ``ranges`` are cached (single-flight, vectored) and
        return the byte total; ``pinned=True`` lands them in — or
        promotes covering entries into — the metadata tier.  The
        lookup face pins a file's probe metadata through this."""
        bufs = self._cache.fetch_many(
            self.key, list(ranges), self._read_storage, pinned=pinned
        )
        return sum(len(b) for b in bufs)

    def close(self) -> None:
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
