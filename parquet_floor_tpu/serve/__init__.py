"""parquet_floor_tpu.serve — the multi-tenant dataset-serving layer.

Three pieces compose the serving story on top of the scan/remote/data
stack (``docs/serving.md``):

* :class:`SharedBufferCache` / :class:`CachedSource` — one process-wide
  two-tier byte cache (pinned metadata, LRU data extents) with
  single-flight storage reads, dropped into the existing scan source
  chain (``serve.cache``);
* :class:`Serving` / :class:`Tenant` — per-tenant budget admission,
  weighted-fair scheduling of storage reads, and per-tenant tracer
  scopes so every client gets its own
  :class:`~parquet_floor_tpu.utils.trace.ScanReport`
  (``serve.tenancy``);
* :class:`Dataset` — point/range lookups descending the format's
  pruning ladder (footer stats → bloom filter → page indexes) to read
  exactly the candidate page(s) (``serve.lookup``).
"""

from .cache import CachedSource, SharedBufferCache, source_key
from .lookup import Dataset
from .slo import SloMonitor, SloStatus, SloTarget
from .tenancy import Serving, Tenant

__all__ = [
    "CachedSource",
    "Dataset",
    "Serving",
    "SharedBufferCache",
    "SloMonitor",
    "SloStatus",
    "SloTarget",
    "Tenant",
    "source_key",
]
