"""parquet_floor_tpu.serve — the multi-tenant dataset-serving layer.

The pieces compose the serving story on top of the scan/remote/data
stack (``docs/serving.md``):

* :class:`SharedBufferCache` / :class:`CachedSource` — one process-wide
  two-tier byte cache (pinned metadata, LRU data extents) with
  single-flight storage reads, dropped into the existing scan source
  chain (``serve.cache``);
* :class:`ShmCacheTier` — the CROSS-PROCESS tier below it: one
  shared-memory segment per host with lease-based cross-process
  single-flight, so N worker processes issue one storage read per
  unique range between them (``serve.shm_cache``);
* :class:`Serving` / :class:`Tenant` — per-tenant budget admission,
  weighted-fair scheduling of BOTH storage reads and decode-engine
  time (the device-WFQ gate), and per-tenant tracer scopes so every
  client gets its own
  :class:`~parquet_floor_tpu.utils.trace.ScanReport`
  (``serve.tenancy``);
* :class:`Dataset` / :class:`RangeCursor` — point/range lookups
  descending the format's pruning ladder (footer stats → bloom filter
  → page indexes) to read exactly the candidate page(s), with a
  bounded-memory resumable cursor face and per-file negative-lookup
  caching (``serve.lookup``);
* :class:`ServeDaemon` / :class:`DaemonClient` — the socket front
  door: per-connection tenant attribution, admission control,
  graceful drain, multi-worker metrics fold (``serve.daemon``);
* :class:`FleetCache` / :class:`FleetMembership` / :class:`PeerClient`
  / :class:`TenantRateLimiter` — the CROSS-HOST tier: consistent-hash
  range ownership over an epoch-numbered membership, peer-to-peer
  range fetch with per-peer breakers and origin fallback, hot-range
  replication, epoch fencing, and token-bucket admission limiting
  (``serve.fleet``).
"""

from .cache import CachedSource, SharedBufferCache, source_key
from .daemon import DaemonClient, ServeDaemon
from .fleet import (
    FleetCache,
    FleetMembership,
    PeerClient,
    TenantRateLimiter,
    TokenBucket,
)
from .lookup import Dataset, RangeCursor
from .shm_cache import ShmCacheTier
from .slo import SloMonitor, SloStatus, SloTarget
from .tenancy import Serving, Tenant

__all__ = [
    "CachedSource",
    "DaemonClient",
    "Dataset",
    "FleetCache",
    "FleetMembership",
    "PeerClient",
    "RangeCursor",
    "ServeDaemon",
    "Serving",
    "SharedBufferCache",
    "ShmCacheTier",
    "SloMonitor",
    "SloStatus",
    "SloTarget",
    "Tenant",
    "TenantRateLimiter",
    "TokenBucket",
    "source_key",
]
