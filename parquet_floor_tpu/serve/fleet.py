"""Cross-host fleet cache fabric: k serving daemons as ONE survivable
cache tier.

One host's :class:`~parquet_floor_tpu.serve.shm_cache.ShmCacheTier`
stops at the host boundary: a fleet of k hosts issues k origin reads
per unique range and has no story for a host dying mid-request.  This
module adds the cross-host layer (docs/serving.md):

* **Ownership** — :class:`FleetMembership` assigns every unique range
  an owner by rendezvous (highest-random-weight) hashing over an
  explicit, epoch-numbered member list.  Rendezvous hashing keeps
  reassignment minimal on membership change (only the lost member's
  ranges move) with no ring state to persist.
* **Peer leg** — :class:`FleetCache` presents the exact read-through
  face ``SharedBufferCache`` mounts via ``shm=``; a non-owner fetches a
  missed range from its owner over :class:`PeerClient` instead of
  re-reading origin, so the fleet issues ~one origin read per unique
  range.
* **Failure domain** — every peer gets its own
  :class:`~parquet_floor_tpu.io.remote.CircuitBreaker`; a peer fetch
  has a hard timeout and ONE retry, then the next candidate (the
  replica), then *origin*.  A dead or slow owner therefore degrades to
  a cache miss — latency, never an error.
* **Replication** — ranges an owner serves repeatedly are pushed to
  the next-on-ring member, so losing a host loses capacity, not data.
* **Fencing** — every peer request carries the requester's membership
  epoch; a responder on a different epoch refuses with
  ``stale_epoch`` instead of answering from a stale ownership map.
* **Admission** — :class:`TenantRateLimiter` (token buckets) rejects
  over-rate tenants at the daemon door with ``retry_after_ms`` BEFORE
  they queue into the ``max_pending`` cliff or burn a breaker budget.

``scripts/fleet_smoke.py`` and bench.py's fleet leg drive a 3-daemon
topology through a mid-load host loss and assert exactly-once origin
reads and zero wrong answers (``check_bench_report.check_fleet_leg``).
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..errors import BreakerOpenError
from ..io.remote import CircuitBreaker
from ..utils import trace
from .shm_cache import _digest


@dataclass(frozen=True)
class FleetMembership:
    """An explicit, epoch-numbered fleet member list.  Immutable: every
    change is a NEW membership with a higher epoch, and the epoch rides
    every peer request so two hosts can never trade bytes across
    disagreeing ownership maps (the fencing rule)."""

    epoch: int
    members: Tuple[str, ...]

    @classmethod
    def create(cls, members: Sequence[str],
               epoch: int = 1) -> "FleetMembership":
        members = tuple(sorted(set(members)))
        if not members:
            raise ValueError("fleet membership needs at least one member")
        return cls(epoch=int(epoch), members=members)

    def owners(self, d0: int, d1: int, replicas: int = 2) -> List[str]:
        """The range's owner chain — rendezvous-hash scores, best
        first.  ``[0]`` is the owner, ``[1]`` the replica target; a
        membership change moves only the ranges whose winner left."""
        packed = struct.pack("<QQ", d0 & _U64, d1 & _U64)
        scored = sorted(
            self.members,
            key=lambda m: hashlib.blake2b(
                m.encode("utf-8") + packed, digest_size=8).digest(),
            reverse=True,
        )
        return scored[:max(1, int(replicas))]

    def without(self, member: str) -> "FleetMembership":
        remaining = tuple(m for m in self.members if m != member)
        if not remaining:
            raise ValueError("cannot remove the last fleet member")
        return FleetMembership(epoch=self.epoch + 1, members=remaining)

    def with_member(self, member: str) -> "FleetMembership":
        return FleetMembership(
            epoch=self.epoch + 1,
            members=tuple(sorted(set(self.members) | {member})),
        )


_U64 = (1 << 64) - 1


class TokenBucket:
    """One token bucket: ``rate_per_s`` sustained, ``burst`` capacity.
    ``try_acquire`` never sleeps — it admits, or returns how long the
    caller should wait (the reject-don't-queue admission contract)."""

    def __init__(self, rate_per_s: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError("rate_per_s and burst must be positive")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = float(burst)
        self._stamp = clock()

    def try_acquire(self, n: float = 1.0) -> Optional[float]:
        """None = admitted (n tokens taken); else seconds until n
        tokens will have refilled (the ``retry_after`` hint)."""
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return None
            return (n - self._tokens) / self.rate


class TenantRateLimiter:
    """Per-tenant token buckets, lazily created at first sight of a
    tenant.  The daemon consults this at ADMISSION — before the
    request counts against ``max_pending`` — so an over-rate tenant is
    told to come back later instead of queueing into the overload
    cliff or burning a peer breaker's failure budget."""

    def __init__(self, rate_per_s: float, burst: Optional[float] = None,
                 overrides: Optional[Dict[str, float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate_per_s)
        self.burst = float(burst if burst is not None else 2 * rate_per_s)
        self._overrides = dict(overrides or {})
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}

    def admit(self, tenant: str, n: float = 1.0) -> Optional[float]:
        """None = admitted; else the tenant's ``retry_after`` seconds."""
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                rate = self._overrides.get(tenant, self.rate)
                bucket = TokenBucket(rate, max(self.burst, rate),
                                     clock=self._clock)
                self._buckets[tenant] = bucket
        return bucket.try_acquire(n)


def _close_conn(sock, rfile) -> None:
    if rfile is not None:
        try:
            rfile.close()
        except OSError:
            pass
    if sock is not None:
        try:
            sock.close()
        except OSError:
            pass


class PeerClient:
    """One fleet peer's wire client: newline-delimited JSON over a
    lazily-(re)connected socket, hello-free (fleet ops are
    protocol-plane, admitted before tenant attribution).  Thread-safe
    via connection CHECKOUT — the lock only guards the one-slot cached
    connection, never the round trip itself (FL-LOCK002), so a slow
    peer stalls only its own caller; a concurrent request just dials a
    fresh socket and the surplus one closes on return.  Any transport
    error drops the connection so the next request reconnects fresh.
    A live client holds a socket — close it, or the owning
    :class:`FleetCache`'s ``close()`` does (FL-RES001)."""

    def __init__(self, host: str, port: int, timeout_s: float = 2.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        # midpoint clock-offset samples (peer_clock − our_clock,
        # seconds): every reply's server_ts against our send/receive
        # wall pair — the raw material of the fleet timeline's
        # cross-host alignment (trace.merge_fleet_trace)
        self._offsets: deque = deque(maxlen=64)

    def _drop_locked(self) -> None:
        _close_conn(self._sock, self._rfile)
        self._sock = None
        self._rfile = None

    def request(self, op: str, **fields) -> dict:
        """One round-trip; returns the raw reply dict (callers inspect
        ``ok``/``code`` — a refusal is an answer, not an exception).
        Under an active trace the request line carries the
        :class:`~parquet_floor_tpu.utils.trace.TraceContext`, and every
        reply's ``server_ts`` yields one midpoint clock-offset sample
        for the fleet-timeline merge."""
        msg = {"op": op, **fields}
        ctx = trace.current_context()
        if ctx is not None:
            msg["trace"] = ctx.to_wire()
        payload = (json.dumps(msg) + "\n").encode("utf-8")
        with self._lock:
            sock, rfile = self._sock, self._rfile
            self._sock = self._rfile = None  # checked out
        try:
            if sock is None:
                sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s)
                sock.settimeout(self.timeout_s)
                rfile = sock.makefile("rb")
            t0 = trace.perf_to_unix(time.perf_counter())
            sock.sendall(payload)
            line = rfile.readline()
            t1 = trace.perf_to_unix(time.perf_counter())
        except (OSError, ValueError):
            _close_conn(sock, rfile)
            raise
        if not line:
            _close_conn(sock, rfile)
            raise ConnectionError(
                f"peer {self.host}:{self.port} closed the connection")
        with self._lock:
            if self._closed or self._sock is not None:
                _close_conn(sock, rfile)  # late or surplus: don't cache
            else:
                self._sock, self._rfile = sock, rfile
        reply = json.loads(line)
        sts = reply.get("server_ts") if isinstance(reply, dict) else None
        if isinstance(sts, (int, float)) and not isinstance(sts, bool):
            # midpoint method: the server stamped inside [t0, t1], so
            # its clock minus our RTT midpoint estimates the skew with
            # error bounded by RTT/2 (docs/observability.md)
            off = float(sts) - 0.5 * (t0 + t1)
            with self._lock:
                self._offsets.append(off)
            trace.gauge_max("trace.clock_offset_us", int(abs(off) * 1e6))
        return reply

    def clock_offset(self) -> Optional[float]:
        """Median of the recent midpoint samples (``peer_clock −
        our_clock``, seconds), or None before any reply arrived —
        the median rides out the asymmetric-RTT outliers a loaded
        event loop produces."""
        with self._lock:
            samples = sorted(self._offsets)
        if not samples:
            return None
        m = len(samples) // 2
        if len(samples) % 2:
            return samples[m]
        return 0.5 * (samples[m - 1] + samples[m])

    def epoch(self) -> dict:
        return self.request("fleet_epoch")

    def fetch(self, key: tuple, offset: int, length: int,
              epoch: int) -> dict:
        reply = self.request("fleet_fetch", key=list(key),
                             offset=int(offset), length=int(length),
                             epoch=int(epoch))
        if reply.get("ok") and "data" in reply:
            reply["data"] = base64.b64decode(reply["data"])
        return reply

    def put(self, key: tuple, offset: int, data: bytes, epoch: int,
            pinned: bool = False) -> dict:
        return self.request(
            "fleet_put", key=list(key), offset=int(offset),
            data=base64.b64encode(bytes(data)).decode("ascii"),
            epoch=int(epoch), pinned=bool(pinned))

    def close(self) -> None:
        with self._lock:
            self._closed = True  # an in-flight checkout closes on return
            self._drop_locked()

    def __enter__(self) -> "PeerClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _LocalStore:
    """FleetCache's built-in local range store when no ShmCacheTier is
    mounted: a byte-budget LRU of exact ranges keyed by digest."""

    def __init__(self, capacity_bytes: int = 64 << 20):
        self.capacity = int(capacity_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._bytes = 0

    def get(self, dk: tuple) -> Optional[bytes]:
        with self._lock:
            data = self._entries.get(dk)
            if data is not None:
                self._entries.move_to_end(dk)
            return data

    def put(self, dk: tuple, data: bytes) -> None:
        with self._lock:
            if dk in self._entries:
                return
            self._entries[dk] = data
            self._bytes += len(data)
            while self._bytes > self.capacity and len(self._entries) > 1:
                _, old = self._entries.popitem(last=False)
                self._bytes -= len(old)


class FleetCache:
    """The fleet tier one daemon mounts: local ranges first, then the
    owning PEER, then origin — behind the exact ``read_through(key,
    ranges, read_many_fn, pinned)`` face ``SharedBufferCache`` mounts
    via ``shm=``, so the whole fabric is invisible above L1.

    The peer leg is where the robustness lives: per-peer circuit
    breakers (reusing io/remote's :class:`CircuitBreaker`), a hard
    per-fetch timeout with ONE retry, candidate order [owner, replica],
    and an unconditional origin fallback — no peer failure mode
    surfaces as an error, only as origin latency.  ``serve_range`` /
    ``put_remote`` are the daemon-side faces of the same store, fenced
    by membership epoch.

    Owns its :class:`PeerClient` sockets (``close()`` releases them —
    FL-RES001); a mounted ``inner`` ShmCacheTier stays caller-owned,
    matching the ``SharedBufferCache(shm=tier)`` transfer shape.
    """

    def __init__(self, node_id: str, membership: FleetMembership, *,
                 peers: Optional[dict] = None, inner=None,
                 origin: Optional[Callable] = None,
                 peer_timeout_s: float = 2.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 replicas: int = 2, replicate_after: int = 2,
                 local_bytes: int = 64 << 20,
                 clock: Callable[[], float] = time.monotonic):
        if node_id not in membership.members:
            raise ValueError(f"node {node_id!r} not in membership")
        self.node_id = node_id
        self.peer_timeout_s = float(peer_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.replicas = max(1, int(replicas))
        self.replicate_after = int(replicate_after)
        self._origin = origin
        self._inner = inner
        self._store = _LocalStore(local_bytes) if inner is None else None
        self._clock = clock
        self._admin_lock = threading.Lock()
        self._membership = membership
        self._peers: Dict[str, PeerClient] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._flight_lock = threading.Lock()
        self._flights: Dict[tuple, threading.Event] = {}
        self._heat: Dict[tuple, int] = {}
        self._closed = False
        self.install_membership(membership, peers or {})

    # -- membership / admin -------------------------------------------------

    @property
    def membership(self) -> FleetMembership:
        return self._membership

    @property
    def epoch(self) -> int:
        return self._membership.epoch

    def install_membership(self, membership: FleetMembership,
                           peers: Optional[dict] = None) -> None:
        """Install a NEW (higher-epoch) ownership map, atomically with
        its peer endpoints.  ``peers`` maps member id to a PeerClient
        or a ``(host, port)`` pair; entries for members not in the new
        membership — and replaced clients — are closed here."""
        with self._admin_lock:
            # the monotonicity check must be atomic with the install:
            # two concurrent installs that both pass an unlocked check
            # can commit in either order and move the epoch backwards
            if membership.epoch < self._membership.epoch:
                raise ValueError(
                    f"membership epoch moved backwards: "
                    f"{membership.epoch} < {self._membership.epoch}")
            old = self._peers
            if peers is not None:
                fresh: Dict[str, PeerClient] = {}
                for member, endpoint in peers.items():
                    if member == self.node_id:
                        continue
                    if isinstance(endpoint, PeerClient):
                        fresh[member] = endpoint
                    else:
                        host, port = endpoint
                        fresh[member] = PeerClient(
                            host, port, timeout_s=self.peer_timeout_s)
                self._peers = fresh
                for member, client in old.items():
                    if self._peers.get(member) is not client:
                        client.close()
            self._membership = membership
        trace.decision("serve.fleet", {
            "action": "membership", "node": self.node_id,
            "epoch": membership.epoch,
            "members": list(membership.members),
        })

    def clock_offsets(self) -> Dict[str, float]:
        """Median midpoint clock offset per peer (``peer_clock −
        our_clock``, seconds) for every peer that has answered at
        least once — the per-host alignment input of
        :func:`~parquet_floor_tpu.utils.trace.merge_fleet_trace`."""
        with self._admin_lock:
            peers = dict(self._peers)
        out: Dict[str, float] = {}
        for member, client in peers.items():
            off = client.clock_offset()
            if off is not None:
                out[member] = off
        return out

    def _breaker(self, member: str) -> CircuitBreaker:
        with self._admin_lock:
            breaker = self._breakers.get(member)
            if breaker is None:
                breaker = CircuitBreaker(
                    threshold=self.breaker_threshold,
                    cooldown_s=self.breaker_cooldown_s,
                    name=f"peer:{member}", clock=self._clock)
                self._breakers[member] = breaker
            return breaker

    # -- local store --------------------------------------------------------

    def _local_get(self, key: tuple, offset: int, length: int
                   ) -> Optional[bytes]:
        if self._inner is not None:
            data = self._inner.get(key, offset, length)
            return None if data is None else bytes(data)
        return self._store.get(_digest(key, offset, length))

    def _local_put(self, key: tuple, offset: int, data: bytes,
                   pinned: bool = False) -> None:
        if self._inner is not None:
            self._inner.put(key, offset, data, pinned=pinned)
        else:
            self._store.put(_digest(key, offset, len(data)), bytes(data))

    def _origin_read(self, key: tuple, ranges: List[Tuple[int, int]],
                     read_many_fn, pinned: bool) -> List[bytes]:
        """Read ``ranges`` through the local single-flight layer to the
        origin leg — the path of last resort every failure mode above
        degrades into."""
        with trace.span("serve.fleet_origin_read",
                        attrs={"node": self.node_id,
                               "ranges": len(ranges)}):
            trace.count("serve.fleet_origin_reads", len(ranges))
            if self._inner is not None:
                return [bytes(b) for b in self._inner.read_through(
                    key, ranges, read_many_fn, pinned=pinned)]
            return self._store_read_through(key, ranges, read_many_fn)

    def _store_read_through(self, key: tuple,
                            ranges: List[Tuple[int, int]],
                            read_many_fn) -> List[bytes]:
        out: List[Optional[bytes]] = [None] * len(ranges)
        leads, waits = [], []
        with self._flight_lock:
            for i, (o, n) in enumerate(ranges):
                dk = _digest(key, o, n)
                data = self._store.get(dk)
                if data is not None:
                    out[i] = data
                    continue
                ev = self._flights.get(dk)
                if ev is None:
                    ev = threading.Event()
                    self._flights[dk] = ev
                    leads.append((i, o, n, dk, ev))
                else:
                    waits.append((i, o, n, dk, ev))
        if leads:
            try:
                bufs = read_many_fn([(o, n) for (_, o, n, _, _) in leads])
            except BaseException:
                # wake the waiters; they re-read for themselves below
                with self._flight_lock:
                    for (_, _, _, dk, ev) in leads:
                        self._flights.pop(dk, None)
                        ev.set()
                raise
            for (i, o, n, dk, ev), data in zip(leads, bufs):
                data = bytes(data)
                self._store.put(dk, data)
                out[i] = data
                with self._flight_lock:
                    self._flights.pop(dk, None)
                ev.set()
        for (i, o, n, dk, ev) in waits:
            ev.wait(timeout=30.0)
            data = self._store.get(dk)
            if data is None:
                data = bytes(read_many_fn([(o, n)])[0])
                self._store.put(dk, data)
            out[i] = data
        return out  # type: ignore[return-value]

    # -- the read-through face (mounted under SharedBufferCache) ------------

    def read_through(self, key: tuple, ranges: Sequence[Tuple[int, int]],
                     read_many_fn, pinned: bool = False) -> List[bytes]:
        """The ``shm=`` mount face: local hits, then the owning peer
        for non-primary misses (timeout + one retry + breaker, replica
        next, origin last), then one vectored origin read for
        primary-owned misses and every fallback.  Only the PRIMARY
        reads origin for a miss here — a replica peer-fetches the
        primary like any non-owner, which is what keeps the fleet at
        ~one origin read per unique range (its local copy arrives via
        the fetch, or the primary's replication push).  Every range is
        answered; no peer state can make this raise for a reachable
        origin."""
        ranges = [(int(o), int(n)) for (o, n) in ranges]
        out: List[Optional[bytes]] = [None] * len(ranges)
        membership = self._membership
        owned, remote = [], []
        for i, (o, n) in enumerate(ranges):
            data = self._local_get(key, o, n)
            if data is not None:
                out[i] = data
                continue
            dk = _digest(key, o, n)
            owners = membership.owners(dk[0], dk[1], self.replicas)
            if owners[0] == self.node_id:
                owned.append((i, o, n, dk, owners))
            else:
                remote.append((i, o, n, owners))
        fallback = []
        for (i, o, n, owners) in remote:
            data = self._peer_fetch(key, o, n, owners, membership.epoch)
            if data is None:
                trace.count("serve.fleet_peer_fallbacks")
                fallback.append((i, o, n))
            else:
                out[i] = data
                self._local_put(key, o, data, pinned)
        need = [(i, o, n) for (i, o, n, _, _) in owned] + fallback
        if need:
            bufs = self._origin_read(
                key, [(o, n) for (_, o, n) in need], read_many_fn, pinned)
            for (i, o, n), data in zip(need, bufs):
                out[i] = data
        for (i, o, n, dk, owners) in owned:
            self._maybe_replicate(key, o, out[i], dk, owners,
                                  membership.epoch)
        trace.count("serve.fleet_served", len(ranges))
        return out  # type: ignore[return-value]

    # -- the peer leg -------------------------------------------------------

    def _peer_fetch(self, key: tuple, offset: int, length: int,
                    owners: List[str], epoch: int) -> Optional[bytes]:
        """Bytes from the owner (or its replica), or None → the caller
        falls back to origin.  Per candidate: breaker admission, one
        attempt, ONE retry on a transport failure, then the next
        candidate.  A refusal (miss / draining / overload / stale
        epoch) is an answer — it bypasses the breaker's failure count
        and moves on without a retry."""
        for member in owners:
            if member == self.node_id:
                continue
            with self._admin_lock:
                peer = self._peers.get(member)
            if peer is None:
                continue
            breaker = self._breaker(member)
            try:
                breaker.check()
            except BreakerOpenError:
                continue
            with trace.span("serve.fleet_peer_fetch",
                            attrs={"node": self.node_id, "peer": member,
                                   "length": length}):
                t0 = self._clock()
                reply = None
                for attempt in (0, 1):
                    trace.count("serve.fleet_peer_fetches")
                    try:
                        reply = peer.fetch(key, offset, length, epoch)
                        break
                    except (OSError, ValueError):
                        trace.count("serve.fleet_peer_errors")
                        breaker.on_failure()
                        reply = None
                if reply is None:
                    trace.decision("serve.fleet", {
                        "action": "peer_failed", "node": self.node_id,
                        "peer": member, "offset": offset,
                        "length": length,
                    })
                    continue
                if reply.get("ok") and reply.get("data") is not None:
                    breaker.on_success()
                    data = reply["data"]
                    trace.count("serve.fleet_peer_hits")
                    trace.count("serve.fleet_peer_hit_bytes", len(data))
                    trace.observe("serve.fleet_peer_wait_seconds",
                                  self._clock() - t0)
                    return data
                code = reply.get("code")
                if code == "stale_epoch":
                    trace.count("serve.fleet_epoch_fenced")
                    trace.decision("serve.fleet", {
                        "action": "fence", "node": self.node_id,
                        "peer": member, "ours": epoch,
                        "theirs": reply.get("epoch"),
                    })
                    trace.flight_fire("epoch_fence", {
                        "node": self.node_id, "peer": member,
                        "ours": epoch, "theirs": reply.get("epoch"),
                    })
                breaker.on_bypass()
        return None

    def _maybe_replicate(self, key: tuple, offset: int,
                         data: Optional[bytes], dk: tuple,
                         owners: List[str], epoch: int) -> None:
        """Push a range this PRIMARY keeps serving to the next-on-ring
        member (best-effort: breaker-guarded, never retried, never an
        error) so losing this host loses capacity, not the range."""
        if data is None or len(owners) < 2 or owners[0] != self.node_id:
            return
        with self._admin_lock:
            heat = self._heat.get(dk, 0) + 1
            self._heat[dk] = heat
            if len(self._heat) > 65536:
                self._heat.clear()  # bounded memory; heat re-learns
            peer = self._peers.get(owners[1])
        if heat != self.replicate_after or peer is None:
            return
        breaker = self._breaker(owners[1])
        try:
            breaker.check()
        except BreakerOpenError:
            return
        try:
            reply = peer.put(key, offset, data, epoch)
        except (OSError, ValueError):
            breaker.on_failure()
            return
        breaker.on_bypass()
        if reply.get("ok"):
            trace.count("serve.fleet_replications")

    # -- the daemon-side faces (fleet_fetch / fleet_put ops) ----------------

    def serve_range(self, key: tuple, offset: int, length: int,
                    epoch: int) -> Tuple[str, Optional[bytes]]:
        """Answer a peer's fetch: ``("ok", bytes)``, ``("miss", None)``
        (not here and no origin configured — the asker falls back), or
        ``("stale_epoch", None)`` when the epochs disagree (NEITHER a
        stale owner nor a stale asker may trade bytes).  Unlike
        :meth:`read_through`, a REPLICA reads origin here too: when the
        primary is gone the asker's second candidate still costs the
        fleet one origin read, not one per surviving host."""
        key = tuple(key)
        membership = self._membership
        if int(epoch) != membership.epoch:
            trace.count("serve.fleet_epoch_fenced")
            trace.flight_fire("epoch_fence", {
                "node": self.node_id, "op": "fleet_fetch",
                "ours": membership.epoch, "theirs": int(epoch),
            })
            return "stale_epoch", None
        data = self._local_get(key, offset, length)
        dk = _digest(key, offset, length)
        owners = membership.owners(dk[0], dk[1], self.replicas)
        if data is None and self.node_id in owners and \
                self._origin is not None:
            origin = self._origin
            data = self._origin_read(
                key, [(int(offset), int(length))],
                lambda rs: origin(key, rs), False)[0]
        if data is None:
            return "miss", None
        self._maybe_replicate(key, offset, data, dk, owners,
                              membership.epoch)
        return "ok", data

    def put_remote(self, key: tuple, offset: int, data: bytes,
                   epoch: int, pinned: bool = False) -> str:
        """A peer's replication push; fenced like every fleet op."""
        if int(epoch) != self._membership.epoch:
            trace.count("serve.fleet_epoch_fenced")
            trace.flight_fire("epoch_fence", {
                "node": self.node_id, "op": "fleet_put",
                "ours": self._membership.epoch, "theirs": int(epoch),
            })
            return "stale_epoch"
        self._local_put(tuple(key), int(offset), bytes(data), pinned)
        return "ok"

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._admin_lock:
            peers, self._peers = self._peers, {}
        for client in peers.values():
            client.close()

    def __enter__(self) -> "FleetCache":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
