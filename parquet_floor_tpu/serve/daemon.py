"""The serving daemon — real connections over the serving layer.

PR 9 built the serving *mechanisms* (shared cache, tenant admission,
WFQ, the probe ladder); this module is the process that actually
answers clients: an asyncio socket server speaking newline-delimited
JSON, with

* **per-connection tenant attribution** — a connection's first message
  is ``hello`` naming its tenant (and weight); every subsequent probe
  on that connection runs under that tenant's tracer scope, byte gate,
  and device-time WFQ seat, so one socket == one accountable client;
* **admission control** — requests beyond ``max_pending`` queued +
  in-flight are rejected immediately with ``overloaded`` +
  ``retry_after_ms`` (``serve.daemon_rejected``) instead of growing an
  unbounded queue: an open-loop overload shows up as fast, explicit
  pushback, not as a latency cliff discovered at timeout;
* **bounded execution** — probes run on a ``max_inflight``-wide thread
  pool behind the event loop, so slow storage cannot wedge the
  protocol plane (pings, metrics, drains keep answering);
* **graceful drain** — :meth:`drain` stops accepting, lets in-flight
  requests finish (bounded by a deadline), pushes a final metrics
  snapshot, and reports whether the drain completed clean;
* **multi-worker metrics** — each worker daemon pushes its merged
  per-tenant snapshot to a shared ``metrics_dir``
  (:func:`~parquet_floor_tpu.utils.metrics_export.write_snapshot`);
  the ``metrics`` op (and any
  ``MetricsServer(snapshot_dir=...)`` scraper) folds the directory
  through ``merge_snapshots``, so one scrape sees the whole fleet.

Protocol (one JSON object per line, UTF-8 with surrogateescape so
non-UTF8 BINARY cells survive the wire):

==============  ========================================================
op              request fields → reply fields (all replies carry ``ok``)
==============  ========================================================
``hello``       ``tenant``, ``weight?`` → ``tenant``, ``weight``
``lookup``      ``dataset``, ``key``, ``columns?``, ``limit?`` → ``rows``
``range``       ``dataset``, ``lo``, ``hi``, ``columns?``, ``limit?``
                → ``rows``
``range_page``  ``dataset``, ``lo``, ``hi``, ``columns?``,
                ``page_rows?``, ``cursor?`` → ``rows``, ``cursor``
                (pass the returned cursor back for the next page;
                ``null`` when exhausted)
``select``      ``dataset``, ``exprs`` (list of ``[name, tree]`` —
                the JSON shape of ``Expr.tree()``), ``lo?``/``hi?``
                (key range filter), ``columns?``, ``limit?`` → ``rows``
``join_page``   ``left``, ``right`` (dataset names), ``on`` (key
                columns), ``how?``, ``left_columns?``,
                ``right_columns?``, ``page_rows?``, ``cursor?`` →
                ``rows``, ``cursor`` (stateless resume, as
                ``range_page``)
``metrics``     → ``metrics`` (the folded multi-worker snapshot)
``health``      → ``health`` (the one-page ``Serving.health`` text)
``ping``        → (empty)
``fleet_epoch`` → ``epoch``, ``node`` (fleet-mounted daemons only)
``fleet_fetch`` ``key``, ``offset``, ``length``, ``epoch`` →
                ``data`` (base64) — a peer's range fetch; refused with
                ``stale_epoch`` when the membership epochs disagree
``fleet_put``   ``key``, ``offset``, ``data`` (base64), ``epoch``,
                ``pinned?`` → (empty) — a peer's replication push
==============  ========================================================

Fleet ops are protocol-plane like ``ping`` — no ``hello`` required
(the peer is a daemon, not a tenant) — but their EXECUTION runs on the
same bounded pool and counts against ``max_pending``, so a drain waits
out in-flight peer fetches and overload pushback applies to peers too.

Errors come back as ``{"ok": false, "error": ..., "code": ...}`` with
``code`` one of ``overloaded`` / ``rate_limited`` / ``draining`` /
``hello_required`` / ``bad_request`` / ``stale_epoch``; the connection
stays usable after any of them.  ``rate_limited`` (per-tenant token
bucket, ``rate_limiter=``) carries ``retry_after_ms`` and is checked
BEFORE admission, so an over-rate tenant never occupies a pending slot.
Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..utils import trace
from .lookup import Dataset
from .tenancy import Serving


# one request/reply line may carry a base64 range payload (a peer's
# fleet_put replication push) — asyncio's default 64 KiB readline
# limit would sever the connection for any extent past ~48 KiB
_WIRE_LINE_LIMIT = 32 << 20


def _encode(obj: dict) -> bytes:
    return (json.dumps(obj, ensure_ascii=False) + "\n").encode(
        "utf-8", "surrogateescape"
    )


def _decode(line: bytes) -> dict:
    obj = json.loads(line.decode("utf-8", "surrogateescape"))
    if not isinstance(obj, dict):
        raise ValueError("request must be a JSON object")
    return obj


class ServeDaemon:
    """One serving worker's front door (module docstring).

    The caller owns ``serving`` and the ``datasets`` (close order:
    daemon first, then datasets, then the serving context).  ``port=0``
    binds an ephemeral port — read it back from :attr:`port` after
    :meth:`start`.  ``metrics_dir`` enables the multi-worker metrics
    push (one ``worker-<pid>-<port>.json`` per daemon)."""

    def __init__(self, serving: Serving, datasets: Dict[str, Dataset],
                 host: str = "127.0.0.1", port: int = 0,
                 max_inflight: int = 4, max_pending: int = 64,
                 metrics_dir: Optional[str] = None,
                 drain_timeout_s: float = 30.0,
                 fleet=None, rate_limiter=None,
                 flight_dir: Optional[str] = None,
                 flight_window_s: float = 30.0,
                 flight_debounce_s: float = 5.0):
        if max_inflight <= 0:
            raise ValueError(f"max_inflight must be > 0, got {max_inflight}")
        if max_pending < max_inflight:
            raise ValueError(
                f"max_pending ({max_pending}) must be >= max_inflight "
                f"({max_inflight})"
            )
        self.serving = serving
        self.datasets = dict(datasets)
        self.host = host
        self.port = int(port)
        self.max_inflight = int(max_inflight)
        self.max_pending = int(max_pending)
        self.metrics_dir = metrics_dir
        self.drain_timeout_s = float(drain_timeout_s)
        #: optional FleetCache (serve/fleet.py) — enables the
        #: fleet_epoch / fleet_fetch / fleet_put peer ops
        self.fleet = fleet
        #: optional TenantRateLimiter — consulted before admission
        self.rate_limiter = rate_limiter
        #: daemon-plane counters (connections, rejections, request
        #: totals) — tenant-attributed metrics ride the tenants' own
        #: tracers like everywhere else in serve/
        self.tracer = trace.Tracer(enabled=True)
        #: incident-bundle settings (docs/observability.md): with a
        #: ``flight_dir``, any flight_fire (SLO burn, breaker trip,
        #: epoch fence) dumps the last ``flight_window_s`` of request
        #: traces + merged metrics + health() there, debounced to at
        #: most one bundle per ``flight_debounce_s``
        self.flight_dir = flight_dir
        self.flight_window_s = float(flight_window_s)
        self.flight_debounce_s = float(flight_debounce_s)
        self._flight_last = 0.0
        self._flight_unsub: list = []
        #: this daemon's OWN flight ring — per-daemon instances keep an
        #: in-process fleet's trace fragments attributed to the right
        #: node (the executor activates it per request)
        self._flight = trace.FlightRecorder(
            host=(fleet.node_id if fleet is not None else None)
        )
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="pftpu-daemon",
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._writers: set = set()
        self._pending = 0          # loop-thread-only mutation
        self._draining = False
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        self._closed = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeDaemon":
        """Bind and serve on a background event-loop thread; returns
        self once the socket is listening (raises if the bind fails)."""
        if self._thread is not None:
            raise ValueError("daemon already started")
        self._thread = threading.Thread(
            target=self._run_loop, name="pftpu-daemon-loop", daemon=True,
        )
        self._thread.start()
        self._started.wait()
        if self._start_error is not None:
            self._thread.join()
            self._thread = None
            raise self._start_error
        with trace.using(self.tracer):
            trace.decision("serve.daemon", {
                "action": "start", "host": self.host, "port": self.port,
                "max_inflight": self.max_inflight,
                "max_pending": self.max_pending,
            })
        if self.fleet is None:
            # no fleet node id to borrow: label flight-recorder records
            # by the bound address so an in-process pair stays distinct
            self._flight.host = f"pid{os.getpid()}:{self.port}"
        # flight-trigger subscriptions: phase 0 pushes this worker's
        # snapshot (so every dumper's merge sees it), phase 1 dumps the
        # incident bundle — see utils/trace.py's trigger bus
        self._flight_unsub.append(
            trace.install_flight_trigger(self._flight_push, phase=0)
        )
        if self.flight_dir is not None:
            self._flight_unsub.append(
                trace.install_flight_trigger(self._flight_dump, phase=1)
            )
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(
                asyncio.start_server(self._handle, self.host, self.port,
                                     limit=_WIRE_LINE_LIMIT)
            )
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:
            self._start_error = e
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            loop.close()

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful drain: stop accepting connections, let in-flight
        requests finish (up to ``timeout_s``), push the final metrics
        snapshot.  Returns True when the queue emptied in time.  The
        daemon keeps answering on OPEN connections with ``draining``
        errors, so clients learn to go elsewhere instead of timing
        out; call :meth:`close` to finish shutdown."""
        if self._loop is None or not self._loop.is_running():
            return True
        t = self.drain_timeout_s if timeout_s is None else float(timeout_s)
        fut = asyncio.run_coroutine_threadsafe(
            self._drain_async(t), self._loop
        )
        clean = bool(fut.result(t + 10.0))
        self.push_metrics()
        with trace.using(self.tracer):
            trace.decision("serve.daemon", {
                "action": "drain", "clean": clean,
            })
        return clean

    async def _drain_async(self, timeout_s: float) -> bool:
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = self._loop.time() + timeout_s
        while self._pending > 0 and self._loop.time() < deadline:
            await asyncio.sleep(0.005)
        return self._pending == 0

    def close(self) -> None:
        """Drain (bounded by ``drain_timeout_s``), close every
        connection, stop the loop, release the worker pool;
        idempotent."""
        if self._closed:
            return
        self._closed = True
        for unsub in self._flight_unsub:
            unsub()
        self._flight_unsub.clear()
        if self._loop is not None and self._loop.is_running():
            try:
                self.drain()
            except BaseException:
                pass
            fut = asyncio.run_coroutine_threadsafe(
                self._close_writers(), self._loop
            )
            try:
                fut.result(5.0)
            except BaseException:
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=True)
        try:
            # last gasp, after the drain settled every in-flight probe:
            # a dying daemon's sealed traces must reach ``metrics_dir``
            # or every later incident bundle has dangling parent links
            # for requests that hopped through it
            self.push_metrics()
        except Exception:
            pass

    async def _close_writers(self) -> None:
        for w in list(self._writers):
            try:
                w.close()
            except BaseException:
                pass

    def __enter__(self):
        # ``with ServeDaemon(...) as d`` starts the daemon — the one
        # acquisition shape FL-RES001 blesses without ceremony
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- metrics -------------------------------------------------------------

    def worker_snapshot(self) -> dict:
        """This worker's foldable snapshot: every tenant tracer plus
        the daemon-plane tracer, merged (the per-worker half of the
        multi-process metrics story).  Distributed-tracing extras ride
        along — ``node`` (this daemon's host label), ``traces`` (the
        flight recorder's sealed ring), and ``clock_offsets`` (the
        fleet client's midpoint estimates) — which is what makes the
        per-worker snapshot files mergeable into ONE fleet timeline
        (``trace.merge_fleet_trace``)."""
        from ..utils.metrics_export import merge_snapshots, snapshot

        snaps = [snapshot(self.tracer)]
        snaps.extend(
            snapshot(t.tracer) for t in self.serving.tenants()
        )
        snap = merge_snapshots(snaps)
        fst = self._flight.stats()
        if fst["dropped_traces"] or fst["dropped_spans"]:
            # ring evictions are counted, never silent — mirror the
            # recorder's cumulative drop counts into the fold
            c = snap["counters"]
            c["trace.flight_traces_dropped"] = fst["dropped_traces"]
            c["trace.flight_spans_dropped"] = fst["dropped_spans"]
        snap["node"] = self._flight.host
        snap["traces"] = self._flight.traces()
        if self.fleet is not None:
            offs = self.fleet.clock_offsets()
            if offs:
                snap["clock_offsets"] = offs
        return snap

    def _push_name(self) -> str:
        # pid AND port: several in-process daemons (the fleet bench,
        # the trace smoke) share a pid but must not clobber each
        # other's pushed snapshots
        return f"worker-{os.getpid()}-{self.port}.json"

    def push_metrics(self) -> Optional[str]:
        """Write this worker's snapshot into ``metrics_dir`` (atomic;
        one file per daemon).  No-op without a ``metrics_dir``."""
        if self.metrics_dir is None:
            return None
        from ..utils.metrics_export import write_snapshot

        path = os.path.join(self.metrics_dir, self._push_name())
        write_snapshot(self.worker_snapshot(), path)
        return path

    def merged_metrics(self) -> dict:
        """The fleet view: every worker snapshot under ``metrics_dir``
        (this worker's live state included) folded through
        ``merge_snapshots``; without a ``metrics_dir``, just this
        worker."""
        own = self.worker_snapshot()
        if self.metrics_dir is None:
            return own
        from ..utils.metrics_export import merge_snapshot_dir

        # our own stale push is excluded: the live snapshot supersedes
        return merge_snapshot_dir(
            self.metrics_dir, extra=[own],
            exclude=[self._push_name()],
        )

    # -- the flight recorder (docs/observability.md) -------------------------

    def _worker_snaps(self) -> list:
        """Every worker snapshot INDIVIDUALLY (this daemon's live one
        plus each file under ``metrics_dir``) — the fleet-timeline
        merge needs per-node identity, so this is NOT the metrics fold.
        A torn file is skipped here (an incident dump is best-effort
        forensics, not the metrics contract)."""
        snaps = [self.worker_snapshot()]
        if self.metrics_dir is not None:
            import pathlib

            own = self._flight.host
            for p in sorted(pathlib.Path(self.metrics_dir).glob("*.json")):
                try:
                    s = json.loads(p.read_text())
                except (OSError, ValueError):
                    continue
                if isinstance(s, dict) and s.get("node") != own:
                    snaps.append(s)
        return snaps

    def _flight_push(self, reason: str, detail: dict) -> None:
        """Phase-0 trigger subscriber: land this worker's snapshot in
        ``metrics_dir`` so every phase-1 dumper's merge sees it."""
        try:
            self.push_metrics()
        except Exception:
            pass

    def _flight_dump(self, reason: str, detail: dict) -> Optional[str]:
        """Phase-1 trigger subscriber: write one incident bundle (the
        last ``flight_window_s`` of traces, the merged metrics
        snapshot, ``health()``, and the fleet timeline), debounced to
        one bundle per ``flight_debounce_s``.  Returns the bundle path
        (None when debounced)."""
        now = time.perf_counter()
        if now - self._flight_last < self.flight_debounce_s:
            return None
        self._flight_last = now
        try:
            health = self.serving.health()
        except Exception as e:
            health = f"health() failed: {type(e).__name__}: {e}"
        try:
            metrics = self.merged_metrics()
        except Exception:
            metrics = None
        path = trace.write_incident_bundle(
            self.flight_dir, reason,
            traces=self._flight.traces(last_s=self.flight_window_s),
            snaps=self._worker_snaps(),
            metrics=metrics,
            health_text=health,
            detail={**detail, "node": self._flight.host},
        )
        with trace.using(self.tracer):
            trace.count("serve.flight_dumps")
            trace.decision("serve.flight", {
                "reason": reason, "path": path,
            })
        return path

    # -- the protocol --------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        with trace.using(self.tracer):
            trace.count("serve.daemon_connections")
        tenant = None
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ConnectionError, asyncio.IncompleteReadError):
                    break
                except ValueError:
                    # a line past _WIRE_LINE_LIMIT: sever rather than
                    # buffer without bound (asyncio LimitOverrunError
                    # surfaces as ValueError from readline)
                    break
                if not line:
                    break
                try:
                    req = _decode(line)
                    op = req.get("op")
                except ValueError as e:
                    writer.write(_encode({
                        "ok": False, "code": "bad_request",
                        "error": f"malformed request: {e}",
                    }))
                    await writer.drain()
                    continue
                if op == "hello":
                    tenant, reply = self._hello(req)
                elif op == "ping":
                    reply = {"ok": True}
                elif op in ("fleet_epoch", "fleet_fetch", "fleet_put"):
                    # peer-plane: a fleet peer is a daemon, not a
                    # tenant — no hello, but execution is bounded and
                    # drain-visible (see _fleet_dispatch)
                    reply = await self._fleet_dispatch(req, op)
                elif op in ("metrics", "health"):
                    # protocol-plane like ping: a scraper (e.g. a
                    # cross-host MetricsServer peers= fold) is not a
                    # tenant — no hello required
                    reply = await self._dispatch(tenant, req, op)
                elif tenant is None:
                    reply = {
                        "ok": False, "code": "hello_required",
                        "error": "first message must be op=hello",
                    }
                elif self._draining and op not in ("metrics", "health"):
                    reply = {
                        "ok": False, "code": "draining",
                        "error": "daemon is draining",
                    }
                else:
                    reply = await self._dispatch(tenant, req, op)
                try:
                    # every reply carries the server's wall clock at
                    # send time — inside the client's [t0, t1] RTT
                    # window by construction, which is exactly what the
                    # midpoint clock-offset estimate needs
                    reply["server_ts"] = trace.perf_to_unix(
                        time.perf_counter()
                    )
                    writer.write(_encode(reply))
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    break
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except BaseException:
                pass

    def _hello(self, req: dict):
        name = req.get("tenant")
        if not name or not isinstance(name, str):
            return None, {
                "ok": False, "code": "bad_request",
                "error": "hello requires a tenant name",
            }
        try:
            weight = float(req.get("weight", 1.0))
            tenant = self.serving.tenant(name, weight)
        except (TypeError, ValueError) as e:
            # a malformed weight is a client error, not a dead
            # connection: the contract says every bad request answers
            # bad_request and the connection stays usable
            return None, {
                "ok": False, "code": "bad_request", "error": str(e),
            }
        return tenant, {"ok": True, "tenant": name, "weight": weight}

    async def _fleet_dispatch(self, req: dict, op: str) -> dict:
        """A peer's fleet op.  ``fleet_epoch`` is a liveness probe and
        always answers; fetch/put run on the worker pool COUNTED in
        ``_pending`` — so ``drain()`` waits out an in-flight peer
        fetch, and ``max_pending`` pushback tells an overloaded
        neighbor to go to origin instead of queueing here."""
        if self.fleet is None:
            return {"ok": False, "code": "bad_request",
                    "error": "daemon has no fleet mount"}
        if op == "fleet_epoch":
            return {"ok": True, "epoch": self.fleet.epoch,
                    "node": self.fleet.node_id}
        if self._draining:
            return {"ok": False, "code": "draining",
                    "error": "daemon is draining"}
        if self._pending >= self.max_pending:
            with trace.using(self.tracer):
                trace.count("serve.daemon_rejected")
            return {
                "ok": False, "code": "overloaded",
                "error": "daemon at max_pending",
                "retry_after_ms": 20 * self.max_pending,
            }
        self._pending += 1
        with trace.using(self.tracer):
            trace.count("serve.daemon_requests")
            trace.gauge_max("serve.daemon_inflight_max", self._pending)
            ctx = trace.TraceContext.from_wire(req.get("trace"))
        try:
            return await self._loop.run_in_executor(
                self._pool, self._fleet_execute, req, op, ctx
            )
        except Exception as e:
            return {"ok": False, "code": "bad_request",
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self._pending -= 1

    def _fleet_execute(self, req: dict, op: str, ctx=None) -> dict:
        # ctx + recorder are activated EXPLICITLY: run_in_executor does
        # not propagate contextvars, and each daemon's flight ring must
        # receive only its own node's span fragments
        with trace.using(self.tracer), \
                trace.use_flight_recorder(self._flight), \
                trace.use_context(ctx):
            with trace.span("serve.fleet_serve", attrs={"op": op}):
                key = tuple(req["key"])
                epoch = int(req.get("epoch", -1))
                if op == "fleet_fetch":
                    status, data = self.fleet.serve_range(
                        key, int(req["offset"]), int(req["length"]), epoch)
                    if status != "ok":
                        return {"ok": False, "code": status,
                                "error": f"fleet fetch: {status}",
                                "epoch": self.fleet.epoch}
                    return {"ok": True, "data": base64.b64encode(
                        data).decode("ascii")}
                status = self.fleet.put_remote(
                    key, int(req["offset"]),
                    base64.b64decode(req["data"]), epoch,
                    pinned=bool(req.get("pinned", False)))
                if status != "ok":
                    return {"ok": False, "code": status,
                            "error": f"fleet put: {status}",
                            "epoch": self.fleet.epoch}
                return {"ok": True}

    async def _dispatch(self, tenant, req: dict, op: str) -> dict:
        if op in ("metrics", "health"):
            # protocol-plane ops: cheap, never queued behind probes
            try:
                if op == "metrics":
                    return {"ok": True, "metrics": self.merged_metrics()}
                return {"ok": True, "health": self.serving.health()}
            except Exception as e:
                return {"ok": False, "code": "bad_request",
                        "error": f"{type(e).__name__}: {e}"}
        if op not in ("lookup", "range", "range_page", "select",
                      "join_page"):
            return {"ok": False, "code": "bad_request",
                    "error": f"unknown op {op!r}"}
        # per-tenant rate limit, BEFORE admission: an over-rate tenant
        # is told when to come back without ever occupying a pending
        # slot (or burning a downstream breaker's failure budget)
        if self.rate_limiter is not None:
            retry_s = self.rate_limiter.admit(tenant.name)
            if retry_s is not None:
                with trace.using(tenant.tracer):
                    trace.count("serve.ratelimit_rejected")
                return {
                    "ok": False, "code": "rate_limited",
                    "error": f"tenant {tenant.name!r} over rate",
                    "retry_after_ms": max(1, int(retry_s * 1000)),
                }
        # admission: pending (queued + in-flight) is bounded — beyond
        # it the daemon pushes back NOW instead of queueing into a
        # latency cliff.  _pending mutates only on the loop thread.
        if self._pending >= self.max_pending:
            with trace.using(self.tracer):
                trace.count("serve.daemon_rejected")
            return {
                "ok": False, "code": "overloaded",
                "error": "daemon at max_pending",
                "retry_after_ms": 20 * self.max_pending,
            }
        self._pending += 1
        with trace.using(self.tracer):
            trace.count("serve.daemon_requests")
            trace.gauge_max("serve.daemon_inflight_max", self._pending)
        with trace.using(tenant.tracer):
            ctx = trace.TraceContext.from_wire(req.get("trace"))
        t0 = time.perf_counter()
        try:
            return await self._loop.run_in_executor(
                self._pool, self._execute, tenant, req, op, ctx
            )
        except Exception as e:
            return {"ok": False, "code": "bad_request",
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self._pending -= 1
            with trace.using(tenant.tracer):
                trace.observe("serve.daemon_request_seconds",
                              time.perf_counter() - t0)

    def _execute(self, tenant, req: dict, op: str, ctx=None) -> dict:
        """One probe, on a pool thread, attributed to the connection's
        tenant (tracer + byte gate + device WFQ all ride ``tenant=``).
        The wire :class:`~parquet_floor_tpu.utils.trace.TraceContext`
        (when the client sent one) and this daemon's flight ring are
        activated explicitly — run_in_executor does not propagate
        contextvars — so every span below joins the client's trace with
        a correct parent link."""
        if ctx is not None and ctx.tenant is None:
            # the hello names the tenant even when the asker's trace
            # began before it knew one: stamp the connection's truth so
            # every daemon-side span attributes correctly
            ctx.tenant = tenant.name
        with trace.using(tenant.tracer), \
                trace.use_flight_recorder(self._flight), \
                trace.use_context(ctx):
            with trace.span("serve.daemon_request",
                            attrs={"op": op, "tenant": tenant.name}):
                return self._execute_op(tenant, req, op)

    def _execute_op(self, tenant, req: dict, op: str) -> dict:
        if op == "join_page":
            return self._join_page(tenant, req)
        ds = self.datasets.get(req.get("dataset"))
        if ds is None:
            return {
                "ok": False, "code": "bad_request",
                "error": f"unknown dataset {req.get('dataset')!r} "
                         f"(have {sorted(self.datasets)})",
            }
        columns = req.get("columns")
        if op == "select":
            from ..query.expr import tree_from_json

            raw = req.get("exprs")
            if not isinstance(raw, list) or not raw:
                return {"ok": False, "code": "bad_request",
                        "error": "select requires exprs: a non-empty "
                                 "list of [name, tree] pairs"}
            try:
                exprs = tuple(
                    (name, tree_from_json(t)) for name, t in raw
                )
            except (TypeError, ValueError) as e:
                return {"ok": False, "code": "bad_request",
                        "error": f"malformed expression: {e}"}
            from ..batch.predicate import col as _col

            pred = None
            if "lo" in req or "hi" in req:
                pred = (_col(ds.key_column) >= req["lo"]) & \
                    (_col(ds.key_column) <= req["hi"])
            rows = ds.select(exprs, predicate=pred, columns=columns,
                             tenant=tenant, limit=req.get("limit"))
            return {"ok": True, "rows": rows}
        if op == "lookup":
            rows = ds.lookup(req["key"], columns=columns, tenant=tenant,
                             limit=req.get("limit"))
            return {"ok": True, "rows": rows}
        if op == "range":
            rows = ds.range(req["lo"], req["hi"], columns=columns,
                            tenant=tenant, limit=req.get("limit"))
            return {"ok": True, "rows": rows}
        # range_page: one bounded page per request — the daemon stays
        # stateless across pages (the cursor token IS the state)
        cur = ds.range_cursor(
            req["lo"], req["hi"], columns=columns, tenant=tenant,
            page_rows=int(req.get("page_rows", 256)),
            cursor=req.get("cursor"),
        )
        rows = cur.next_page()
        return {"ok": True, "rows": rows, "cursor": cur.token}

    def _join_page(self, tenant, req: dict) -> dict:
        """One bounded page of a sorted-merge join (docs/query.md) —
        stateless across requests exactly like ``range_page``: the
        fingerprinted cursor token IS the state, so any worker serving
        the same datasets can answer the next page."""
        from ..query.join import JoinCursor

        sides = {}
        for field in ("left", "right"):
            ds = self.datasets.get(req.get(field))
            if ds is None:
                return {
                    "ok": False, "code": "bad_request",
                    "error": f"unknown {field} dataset "
                             f"{req.get(field)!r} "
                             f"(have {sorted(self.datasets)})",
                }
            sides[field] = ds
        on = req.get("on")
        if not isinstance(on, list) or not on:
            return {"ok": False, "code": "bad_request",
                    "error": "join_page requires on: a non-empty list "
                             "of key columns"}
        with JoinCursor(
            sides["left"], sides["right"], on,
            how=req.get("how", "inner"),
            left_columns=req.get("left_columns"),
            right_columns=req.get("right_columns"),
            tenant=tenant,
            page_rows=int(req.get("page_rows", 256)),
            cursor=req.get("cursor"),
        ) as cur:
            rows = cur.next_page()
            return {"ok": True, "rows": rows, "cursor": cur.token}


class DaemonClient:
    """Minimal synchronous client for :class:`ServeDaemon` (tests,
    smokes, and the bench speak through this).  One socket, one
    tenant: the constructor sends ``hello`` and raises on a rejected
    registration.  Thread-compatible only (callers serialize; open one
    client per thread for concurrency)."""

    def __init__(self, host: str, port: int, tenant: str,
                 weight: float = 1.0, timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        try:
            self._rfile = self._sock.makefile("rb")
            reply = self.request("hello", tenant=tenant, weight=weight)
            if not reply.get("ok"):
                raise RuntimeError(
                    f"hello rejected: {reply.get('error')}"
                )
        except BaseException:
            self._sock.close()
            raise
        self.tenant = tenant

    def request(self, op: str, **fields) -> dict:
        """Send one op, return the raw reply envelope (``ok`` etc.).

        Under an active trace (``trace.start_trace``), the round trip
        is a ``serve.client_request`` span and its context rides the
        request line's ``trace`` field, so the daemon's spans — and any
        peer hops IT makes — join this request's causal chain with the
        client span as parent (docs/observability.md)."""
        with trace.span("serve.client_request", attrs={"op": op}):
            payload = {"op": op, **fields}
            ctx = trace.current_context()
            if ctx is not None:
                payload["trace"] = ctx.to_wire()
            self._sock.sendall(_encode(payload))
            line = self._rfile.readline()
            if not line:
                raise ConnectionError("daemon closed the connection")
            return _decode(line)

    def _checked(self, reply: dict) -> dict:
        if not reply.get("ok"):
            raise RuntimeError(
                f"daemon error [{reply.get('code')}]: {reply.get('error')}"
            )
        return reply

    def lookup(self, dataset: str, key, columns=None, limit=None) -> list:
        return self._checked(self.request(
            "lookup", dataset=dataset, key=key, columns=columns,
            limit=limit,
        ))["rows"]

    def range(self, dataset: str, lo, hi, columns=None,
              limit=None) -> list:
        return self._checked(self.request(
            "range", dataset=dataset, lo=lo, hi=hi, columns=columns,
            limit=limit,
        ))["rows"]

    def range_page(self, dataset: str, lo, hi, columns=None,
                   page_rows: int = 256, cursor=None):
        """One page of a streamed range: ``(rows, next_cursor)`` —
        pass ``next_cursor`` back in until it comes back None."""
        r = self._checked(self.request(
            "range_page", dataset=dataset, lo=lo, hi=hi,
            columns=columns, page_rows=page_rows, cursor=cursor,
        ))
        return r["rows"], r.get("cursor")

    def select(self, dataset: str, exprs, lo=None, hi=None,
               columns=None, limit=None) -> list:
        """Projection-expression query: ``exprs`` is a list of
        ``(name, expr_or_tree)`` pairs (``Expr`` objects are exported
        via ``.tree()`` for the wire)."""
        wire = []
        for name, e in exprs:
            t = e.tree() if hasattr(e, "tree") else e
            wire.append([name, t])
        fields = {"dataset": dataset, "exprs": wire, "columns": columns,
                  "limit": limit}
        if lo is not None or hi is not None:
            fields["lo"], fields["hi"] = lo, hi
        return self._checked(self.request("select", **fields))["rows"]

    def join_page(self, left: str, right: str, on, how: str = "inner",
                  left_columns=None, right_columns=None,
                  page_rows: int = 256, cursor=None):
        """One page of a sorted-merge join: ``(rows, next_cursor)`` —
        pass ``next_cursor`` back in until it comes back None."""
        r = self._checked(self.request(
            "join_page", left=left, right=right, on=list(on), how=how,
            left_columns=left_columns, right_columns=right_columns,
            page_rows=page_rows, cursor=cursor,
        ))
        return r["rows"], r.get("cursor")

    def metrics(self) -> dict:
        return self._checked(self.request("metrics"))["metrics"]

    def health(self) -> str:
        return self._checked(self.request("health"))["health"]

    def ping(self) -> bool:
        return bool(self.request("ping").get("ok"))

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
