"""The serving daemon — real connections over the serving layer.

PR 9 built the serving *mechanisms* (shared cache, tenant admission,
WFQ, the probe ladder); this module is the process that actually
answers clients: an asyncio socket server speaking newline-delimited
JSON, with

* **per-connection tenant attribution** — a connection's first message
  is ``hello`` naming its tenant (and weight); every subsequent probe
  on that connection runs under that tenant's tracer scope, byte gate,
  and device-time WFQ seat, so one socket == one accountable client;
* **admission control** — requests beyond ``max_pending`` queued +
  in-flight are rejected immediately with ``overloaded`` +
  ``retry_after_ms`` (``serve.daemon_rejected``) instead of growing an
  unbounded queue: an open-loop overload shows up as fast, explicit
  pushback, not as a latency cliff discovered at timeout;
* **bounded execution** — probes run on a ``max_inflight``-wide thread
  pool behind the event loop, so slow storage cannot wedge the
  protocol plane (pings, metrics, drains keep answering);
* **graceful drain** — :meth:`drain` stops accepting, lets in-flight
  requests finish (bounded by a deadline), pushes a final metrics
  snapshot, and reports whether the drain completed clean;
* **multi-worker metrics** — each worker daemon pushes its merged
  per-tenant snapshot to a shared ``metrics_dir``
  (:func:`~parquet_floor_tpu.utils.metrics_export.write_snapshot`);
  the ``metrics`` op (and any
  ``MetricsServer(snapshot_dir=...)`` scraper) folds the directory
  through ``merge_snapshots``, so one scrape sees the whole fleet.

Protocol (one JSON object per line, UTF-8 with surrogateescape so
non-UTF8 BINARY cells survive the wire):

==============  ========================================================
op              request fields → reply fields (all replies carry ``ok``)
==============  ========================================================
``hello``       ``tenant``, ``weight?`` → ``tenant``, ``weight``
``lookup``      ``dataset``, ``key``, ``columns?``, ``limit?`` → ``rows``
``range``       ``dataset``, ``lo``, ``hi``, ``columns?``, ``limit?``
                → ``rows``
``range_page``  ``dataset``, ``lo``, ``hi``, ``columns?``,
                ``page_rows?``, ``cursor?`` → ``rows``, ``cursor``
                (pass the returned cursor back for the next page;
                ``null`` when exhausted)
``metrics``     → ``metrics`` (the folded multi-worker snapshot)
``health``      → ``health`` (the one-page ``Serving.health`` text)
``ping``        → (empty)
``fleet_epoch`` → ``epoch``, ``node`` (fleet-mounted daemons only)
``fleet_fetch`` ``key``, ``offset``, ``length``, ``epoch`` →
                ``data`` (base64) — a peer's range fetch; refused with
                ``stale_epoch`` when the membership epochs disagree
``fleet_put``   ``key``, ``offset``, ``data`` (base64), ``epoch``,
                ``pinned?`` → (empty) — a peer's replication push
==============  ========================================================

Fleet ops are protocol-plane like ``ping`` — no ``hello`` required
(the peer is a daemon, not a tenant) — but their EXECUTION runs on the
same bounded pool and counts against ``max_pending``, so a drain waits
out in-flight peer fetches and overload pushback applies to peers too.

Errors come back as ``{"ok": false, "error": ..., "code": ...}`` with
``code`` one of ``overloaded`` / ``rate_limited`` / ``draining`` /
``hello_required`` / ``bad_request`` / ``stale_epoch``; the connection
stays usable after any of them.  ``rate_limited`` (per-tenant token
bucket, ``rate_limiter=``) carries ``retry_after_ms`` and is checked
BEFORE admission, so an over-rate tenant never occupies a pending slot.
Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..utils import trace
from .lookup import Dataset
from .tenancy import Serving


# one request/reply line may carry a base64 range payload (a peer's
# fleet_put replication push) — asyncio's default 64 KiB readline
# limit would sever the connection for any extent past ~48 KiB
_WIRE_LINE_LIMIT = 32 << 20


def _encode(obj: dict) -> bytes:
    return (json.dumps(obj, ensure_ascii=False) + "\n").encode(
        "utf-8", "surrogateescape"
    )


def _decode(line: bytes) -> dict:
    obj = json.loads(line.decode("utf-8", "surrogateescape"))
    if not isinstance(obj, dict):
        raise ValueError("request must be a JSON object")
    return obj


class ServeDaemon:
    """One serving worker's front door (module docstring).

    The caller owns ``serving`` and the ``datasets`` (close order:
    daemon first, then datasets, then the serving context).  ``port=0``
    binds an ephemeral port — read it back from :attr:`port` after
    :meth:`start`.  ``metrics_dir`` enables the multi-worker metrics
    push (one ``worker-<pid>.json`` per daemon)."""

    def __init__(self, serving: Serving, datasets: Dict[str, Dataset],
                 host: str = "127.0.0.1", port: int = 0,
                 max_inflight: int = 4, max_pending: int = 64,
                 metrics_dir: Optional[str] = None,
                 drain_timeout_s: float = 30.0,
                 fleet=None, rate_limiter=None):
        if max_inflight <= 0:
            raise ValueError(f"max_inflight must be > 0, got {max_inflight}")
        if max_pending < max_inflight:
            raise ValueError(
                f"max_pending ({max_pending}) must be >= max_inflight "
                f"({max_inflight})"
            )
        self.serving = serving
        self.datasets = dict(datasets)
        self.host = host
        self.port = int(port)
        self.max_inflight = int(max_inflight)
        self.max_pending = int(max_pending)
        self.metrics_dir = metrics_dir
        self.drain_timeout_s = float(drain_timeout_s)
        #: optional FleetCache (serve/fleet.py) — enables the
        #: fleet_epoch / fleet_fetch / fleet_put peer ops
        self.fleet = fleet
        #: optional TenantRateLimiter — consulted before admission
        self.rate_limiter = rate_limiter
        #: daemon-plane counters (connections, rejections, request
        #: totals) — tenant-attributed metrics ride the tenants' own
        #: tracers like everywhere else in serve/
        self.tracer = trace.Tracer(enabled=True)
        self._pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="pftpu-daemon",
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._writers: set = set()
        self._pending = 0          # loop-thread-only mutation
        self._draining = False
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        self._closed = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServeDaemon":
        """Bind and serve on a background event-loop thread; returns
        self once the socket is listening (raises if the bind fails)."""
        if self._thread is not None:
            raise ValueError("daemon already started")
        self._thread = threading.Thread(
            target=self._run_loop, name="pftpu-daemon-loop", daemon=True,
        )
        self._thread.start()
        self._started.wait()
        if self._start_error is not None:
            self._thread.join()
            self._thread = None
            raise self._start_error
        with trace.using(self.tracer):
            trace.decision("serve.daemon", {
                "action": "start", "host": self.host, "port": self.port,
                "max_inflight": self.max_inflight,
                "max_pending": self.max_pending,
            })
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            self._server = loop.run_until_complete(
                asyncio.start_server(self._handle, self.host, self.port,
                                     limit=_WIRE_LINE_LIMIT)
            )
            self.port = self._server.sockets[0].getsockname()[1]
        except BaseException as e:
            self._start_error = e
            self._started.set()
            loop.close()
            return
        self._started.set()
        try:
            loop.run_forever()
        finally:
            pending = asyncio.all_tasks(loop)
            for task in pending:
                task.cancel()
            if pending:
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
            loop.close()

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful drain: stop accepting connections, let in-flight
        requests finish (up to ``timeout_s``), push the final metrics
        snapshot.  Returns True when the queue emptied in time.  The
        daemon keeps answering on OPEN connections with ``draining``
        errors, so clients learn to go elsewhere instead of timing
        out; call :meth:`close` to finish shutdown."""
        if self._loop is None or not self._loop.is_running():
            return True
        t = self.drain_timeout_s if timeout_s is None else float(timeout_s)
        fut = asyncio.run_coroutine_threadsafe(
            self._drain_async(t), self._loop
        )
        clean = bool(fut.result(t + 10.0))
        self.push_metrics()
        with trace.using(self.tracer):
            trace.decision("serve.daemon", {
                "action": "drain", "clean": clean,
            })
        return clean

    async def _drain_async(self, timeout_s: float) -> bool:
        self._draining = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = self._loop.time() + timeout_s
        while self._pending > 0 and self._loop.time() < deadline:
            await asyncio.sleep(0.005)
        return self._pending == 0

    def close(self) -> None:
        """Drain (bounded by ``drain_timeout_s``), close every
        connection, stop the loop, release the worker pool;
        idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._loop is not None and self._loop.is_running():
            try:
                self.drain()
            except BaseException:
                pass
            fut = asyncio.run_coroutine_threadsafe(
                self._close_writers(), self._loop
            )
            try:
                fut.result(5.0)
            except BaseException:
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._pool.shutdown(wait=True)

    async def _close_writers(self) -> None:
        for w in list(self._writers):
            try:
                w.close()
            except BaseException:
                pass

    def __enter__(self):
        # ``with ServeDaemon(...) as d`` starts the daemon — the one
        # acquisition shape FL-RES001 blesses without ceremony
        if self._thread is None:
            self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- metrics -------------------------------------------------------------

    def worker_snapshot(self) -> dict:
        """This worker's foldable snapshot: every tenant tracer plus
        the daemon-plane tracer, merged (the per-worker half of the
        multi-process metrics story)."""
        from ..utils.metrics_export import merge_snapshots, snapshot

        snaps = [snapshot(self.tracer)]
        snaps.extend(
            snapshot(t.tracer) for t in self.serving.tenants()
        )
        return merge_snapshots(snaps)

    def push_metrics(self) -> Optional[str]:
        """Write this worker's snapshot into ``metrics_dir`` (atomic;
        one file per pid).  No-op without a ``metrics_dir``."""
        if self.metrics_dir is None:
            return None
        from ..utils.metrics_export import write_snapshot

        path = os.path.join(self.metrics_dir, f"worker-{os.getpid()}.json")
        write_snapshot(self.worker_snapshot(), path)
        return path

    def merged_metrics(self) -> dict:
        """The fleet view: every worker snapshot under ``metrics_dir``
        (this worker's live state included) folded through
        ``merge_snapshots``; without a ``metrics_dir``, just this
        worker."""
        own = self.worker_snapshot()
        if self.metrics_dir is None:
            return own
        from ..utils.metrics_export import merge_snapshot_dir

        # our own stale push is excluded: the live snapshot supersedes
        return merge_snapshot_dir(
            self.metrics_dir, extra=[own],
            exclude=[f"worker-{os.getpid()}.json"],
        )

    # -- the protocol --------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        with trace.using(self.tracer):
            trace.count("serve.daemon_connections")
        tenant = None
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ConnectionError, asyncio.IncompleteReadError):
                    break
                except ValueError:
                    # a line past _WIRE_LINE_LIMIT: sever rather than
                    # buffer without bound (asyncio LimitOverrunError
                    # surfaces as ValueError from readline)
                    break
                if not line:
                    break
                try:
                    req = _decode(line)
                    op = req.get("op")
                except ValueError as e:
                    writer.write(_encode({
                        "ok": False, "code": "bad_request",
                        "error": f"malformed request: {e}",
                    }))
                    await writer.drain()
                    continue
                if op == "hello":
                    tenant, reply = self._hello(req)
                elif op == "ping":
                    reply = {"ok": True}
                elif op in ("fleet_epoch", "fleet_fetch", "fleet_put"):
                    # peer-plane: a fleet peer is a daemon, not a
                    # tenant — no hello, but execution is bounded and
                    # drain-visible (see _fleet_dispatch)
                    reply = await self._fleet_dispatch(req, op)
                elif tenant is None:
                    reply = {
                        "ok": False, "code": "hello_required",
                        "error": "first message must be op=hello",
                    }
                elif self._draining and op not in ("metrics", "health"):
                    reply = {
                        "ok": False, "code": "draining",
                        "error": "daemon is draining",
                    }
                else:
                    reply = await self._dispatch(tenant, req, op)
                try:
                    writer.write(_encode(reply))
                    await writer.drain()
                except (ConnectionError, RuntimeError):
                    break
        finally:
            self._writers.discard(writer)
            try:
                writer.close()
            except BaseException:
                pass

    def _hello(self, req: dict):
        name = req.get("tenant")
        if not name or not isinstance(name, str):
            return None, {
                "ok": False, "code": "bad_request",
                "error": "hello requires a tenant name",
            }
        try:
            weight = float(req.get("weight", 1.0))
            tenant = self.serving.tenant(name, weight)
        except (TypeError, ValueError) as e:
            # a malformed weight is a client error, not a dead
            # connection: the contract says every bad request answers
            # bad_request and the connection stays usable
            return None, {
                "ok": False, "code": "bad_request", "error": str(e),
            }
        return tenant, {"ok": True, "tenant": name, "weight": weight}

    async def _fleet_dispatch(self, req: dict, op: str) -> dict:
        """A peer's fleet op.  ``fleet_epoch`` is a liveness probe and
        always answers; fetch/put run on the worker pool COUNTED in
        ``_pending`` — so ``drain()`` waits out an in-flight peer
        fetch, and ``max_pending`` pushback tells an overloaded
        neighbor to go to origin instead of queueing here."""
        if self.fleet is None:
            return {"ok": False, "code": "bad_request",
                    "error": "daemon has no fleet mount"}
        if op == "fleet_epoch":
            return {"ok": True, "epoch": self.fleet.epoch,
                    "node": self.fleet.node_id}
        if self._draining:
            return {"ok": False, "code": "draining",
                    "error": "daemon is draining"}
        if self._pending >= self.max_pending:
            with trace.using(self.tracer):
                trace.count("serve.daemon_rejected")
            return {
                "ok": False, "code": "overloaded",
                "error": "daemon at max_pending",
                "retry_after_ms": 20 * self.max_pending,
            }
        self._pending += 1
        with trace.using(self.tracer):
            trace.count("serve.daemon_requests")
            trace.gauge_max("serve.daemon_inflight_max", self._pending)
        try:
            return await self._loop.run_in_executor(
                self._pool, self._fleet_execute, req, op
            )
        except Exception as e:
            return {"ok": False, "code": "bad_request",
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self._pending -= 1

    def _fleet_execute(self, req: dict, op: str) -> dict:
        with trace.using(self.tracer):
            key = tuple(req["key"])
            epoch = int(req.get("epoch", -1))
            if op == "fleet_fetch":
                status, data = self.fleet.serve_range(
                    key, int(req["offset"]), int(req["length"]), epoch)
                if status != "ok":
                    return {"ok": False, "code": status,
                            "error": f"fleet fetch: {status}",
                            "epoch": self.fleet.epoch}
                return {"ok": True, "data": base64.b64encode(
                    data).decode("ascii")}
            status = self.fleet.put_remote(
                key, int(req["offset"]),
                base64.b64decode(req["data"]), epoch,
                pinned=bool(req.get("pinned", False)))
            if status != "ok":
                return {"ok": False, "code": status,
                        "error": f"fleet put: {status}",
                        "epoch": self.fleet.epoch}
            return {"ok": True}

    async def _dispatch(self, tenant, req: dict, op: str) -> dict:
        if op in ("metrics", "health"):
            # protocol-plane ops: cheap, never queued behind probes
            try:
                if op == "metrics":
                    return {"ok": True, "metrics": self.merged_metrics()}
                return {"ok": True, "health": self.serving.health()}
            except Exception as e:
                return {"ok": False, "code": "bad_request",
                        "error": f"{type(e).__name__}: {e}"}
        if op not in ("lookup", "range", "range_page"):
            return {"ok": False, "code": "bad_request",
                    "error": f"unknown op {op!r}"}
        # per-tenant rate limit, BEFORE admission: an over-rate tenant
        # is told when to come back without ever occupying a pending
        # slot (or burning a downstream breaker's failure budget)
        if self.rate_limiter is not None:
            retry_s = self.rate_limiter.admit(tenant.name)
            if retry_s is not None:
                with trace.using(tenant.tracer):
                    trace.count("serve.ratelimit_rejected")
                return {
                    "ok": False, "code": "rate_limited",
                    "error": f"tenant {tenant.name!r} over rate",
                    "retry_after_ms": max(1, int(retry_s * 1000)),
                }
        # admission: pending (queued + in-flight) is bounded — beyond
        # it the daemon pushes back NOW instead of queueing into a
        # latency cliff.  _pending mutates only on the loop thread.
        if self._pending >= self.max_pending:
            with trace.using(self.tracer):
                trace.count("serve.daemon_rejected")
            return {
                "ok": False, "code": "overloaded",
                "error": "daemon at max_pending",
                "retry_after_ms": 20 * self.max_pending,
            }
        self._pending += 1
        with trace.using(self.tracer):
            trace.count("serve.daemon_requests")
            trace.gauge_max("serve.daemon_inflight_max", self._pending)
        t0 = time.perf_counter()
        try:
            return await self._loop.run_in_executor(
                self._pool, self._execute, tenant, req, op
            )
        except Exception as e:
            return {"ok": False, "code": "bad_request",
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self._pending -= 1
            with trace.using(tenant.tracer):
                trace.observe("serve.daemon_request_seconds",
                              time.perf_counter() - t0)

    def _execute(self, tenant, req: dict, op: str) -> dict:
        """One probe, on a pool thread, attributed to the connection's
        tenant (tracer + byte gate + device WFQ all ride ``tenant=``)."""
        ds = self.datasets.get(req.get("dataset"))
        if ds is None:
            return {
                "ok": False, "code": "bad_request",
                "error": f"unknown dataset {req.get('dataset')!r} "
                         f"(have {sorted(self.datasets)})",
            }
        columns = req.get("columns")
        if op == "lookup":
            rows = ds.lookup(req["key"], columns=columns, tenant=tenant,
                             limit=req.get("limit"))
            return {"ok": True, "rows": rows}
        if op == "range":
            rows = ds.range(req["lo"], req["hi"], columns=columns,
                            tenant=tenant, limit=req.get("limit"))
            return {"ok": True, "rows": rows}
        # range_page: one bounded page per request — the daemon stays
        # stateless across pages (the cursor token IS the state)
        cur = ds.range_cursor(
            req["lo"], req["hi"], columns=columns, tenant=tenant,
            page_rows=int(req.get("page_rows", 256)),
            cursor=req.get("cursor"),
        )
        rows = cur.next_page()
        return {"ok": True, "rows": rows, "cursor": cur.token}


class DaemonClient:
    """Minimal synchronous client for :class:`ServeDaemon` (tests,
    smokes, and the bench speak through this).  One socket, one
    tenant: the constructor sends ``hello`` and raises on a rejected
    registration.  Thread-compatible only (callers serialize; open one
    client per thread for concurrency)."""

    def __init__(self, host: str, port: int, tenant: str,
                 weight: float = 1.0, timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        try:
            self._rfile = self._sock.makefile("rb")
            reply = self.request("hello", tenant=tenant, weight=weight)
            if not reply.get("ok"):
                raise RuntimeError(
                    f"hello rejected: {reply.get('error')}"
                )
        except BaseException:
            self._sock.close()
            raise
        self.tenant = tenant

    def request(self, op: str, **fields) -> dict:
        """Send one op, return the raw reply envelope (``ok`` etc.)."""
        self._sock.sendall(_encode({"op": op, **fields}))
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("daemon closed the connection")
        return _decode(line)

    def _checked(self, reply: dict) -> dict:
        if not reply.get("ok"):
            raise RuntimeError(
                f"daemon error [{reply.get('code')}]: {reply.get('error')}"
            )
        return reply

    def lookup(self, dataset: str, key, columns=None, limit=None) -> list:
        return self._checked(self.request(
            "lookup", dataset=dataset, key=key, columns=columns,
            limit=limit,
        ))["rows"]

    def range(self, dataset: str, lo, hi, columns=None,
              limit=None) -> list:
        return self._checked(self.request(
            "range", dataset=dataset, lo=lo, hi=hi, columns=columns,
            limit=limit,
        ))["rows"]

    def range_page(self, dataset: str, lo, hi, columns=None,
                   page_rows: int = 256, cursor=None):
        """One page of a streamed range: ``(rows, next_cursor)`` —
        pass ``next_cursor`` back in until it comes back None."""
        r = self._checked(self.request(
            "range_page", dataset=dataset, lo=lo, hi=hi,
            columns=columns, page_rows=page_rows, cursor=cursor,
        ))
        return r["rows"], r.get("cursor")

    def metrics(self) -> dict:
        return self._checked(self.request("metrics"))["metrics"]

    def health(self) -> str:
        return self._checked(self.request("health"))["health"]

    def ping(self) -> bool:
        return bool(self.request("ping").get("ok"))

    def close(self) -> None:
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
