"""Per-tenant admission and fair-share scheduling over the shared cache.

The scan scheduler bounds ONE scan's appetite (``ScanOptions.
prefetch_bytes``); a serving process runs MANY concurrent scans for
different clients over one storage system and one shared cache.  This
module adds the missing layer:

* :class:`Serving` — the per-process serving context: one
  :class:`~parquet_floor_tpu.serve.cache.SharedBufferCache`, one global
  prefetch budget, one fair-share gate over storage reads.
* :class:`Tenant` — a registered client with a **weight**.  Each tenant
  gets (a) a proportional slice of the global prefetch budget as its
  scans' ``prefetch_bytes`` (admission: a heavier tenant may keep more
  bytes in flight), (b) a seat in the **weighted-fair queue** over
  storage reads (cache misses) — under contention, grants interleave in
  weight proportion rather than first-come-flood — and (c) its own
  :class:`~parquet_floor_tpu.utils.trace.Tracer` scope, so the
  per-tenant :class:`~parquet_floor_tpu.utils.trace.ScanReport` (cache
  hit rate, stall fraction, bytes from cache vs storage) falls straight
  out of the PR 4 machinery with no new plumbing.

Fair queueing is classic virtual-time WFQ at extent-fetch granularity:
each grant advances the tenant's virtual finish time by
``bytes / weight``; waiters are served in virtual-time order under a
byte-capacity gate on in-flight storage reads.  Cache hits never touch
the gate — fairness arbitrates storage bandwidth, not shared memory.

Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import contextlib
import heapq
import threading
import time
from dataclasses import replace
from typing import Dict, Optional, Sequence

from ..io.source import FileSource
from ..utils import trace
from .cache import CachedSource, SharedBufferCache


class _FairGate:
    """Weighted-fair byte gate over storage reads.

    ``acquire(state, cost)`` blocks until the caller both (a) is the
    earliest waiter by virtual finish time and (b) fits under the
    in-flight byte capacity.  Uncontended acquires (no waiters, fits)
    are a single lock round-trip."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes <= 0:
            raise ValueError(
                f"capacity_bytes must be > 0, got {capacity_bytes}"
            )
        self.capacity = int(capacity_bytes)
        self._cv = threading.Condition()
        self._inflight = 0
        self._vtime = 0.0
        self._heap: list = []   # (vtag, seq, ticket)
        self._seq = 0

    def acquire(self, state: "_TenantShare", cost: int) -> None:
        # one read larger than the whole gate must still pass (alone):
        # clamp its charge to the capacity, mirroring the scan budget's
        # oversized-unit rule
        cost = min(int(cost), self.capacity)
        if cost <= 0:
            return
        with self._cv:
            # the virtual tag is assigned at ARRIVAL (WFQ start time:
            # the later of the system's virtual clock and the tenant's
            # own last finish) and the tenant's finish advances by
            # cost/weight — which is exactly how a heavy tenant's
            # backlog interleaves 2:1 against a light one's instead of
            # queueing FIFO
            vtag = max(self._vtime, state.vfinish)
            state.vfinish = vtag + cost / state.weight
            if not self._heap and self._inflight + cost <= self.capacity:
                self._grant(vtag, cost)
                return
            trace.count("serve.fair_share_waits")
            t_wait = time.perf_counter()
            ticket = [False]  # granted flag, mutated under the cv
            self._seq += 1
            heapq.heappush(self._heap, (vtag, self._seq, ticket, cost))
            while True:
                if self._pump():
                    # a grant may belong to ANOTHER waiter parked in
                    # wait() — it must be woken to see its ticket
                    self._cv.notify_all()
                if ticket[0]:
                    # grant-wait latency of the CONTENDED path (the
                    # uncontended grant above is one lock round-trip and
                    # would only bury the tail in zeros)
                    trace.observe(
                        "serve.fair_wait_seconds",
                        time.perf_counter() - t_wait,
                    )
                    return
                self._cv.wait()

    def _grant(self, vtag: float, cost: int) -> None:
        self._vtime = max(self._vtime, vtag)
        self._inflight += cost
        trace.gauge_max("serve.inflight_storage_bytes_max", self._inflight)

    def _pump(self) -> int:
        """Grant from the head of the virtual-time order while capacity
        lasts (caller holds the cv); returns how many grants were made."""
        granted = 0
        while self._heap:
            vtag, _seq, ticket, cost = self._heap[0]
            if self._inflight + cost > self.capacity:
                break
            heapq.heappop(self._heap)
            self._grant(vtag, cost)
            ticket[0] = True
            granted += 1
        return granted

    def release(self, cost: int) -> None:
        cost = min(int(cost), self.capacity)
        if cost <= 0:
            return
        with self._cv:
            self._inflight -= cost
            self._pump()
            self._cv.notify_all()

    def stats(self) -> dict:
        """One consistent snapshot of the gate — taken under the cv and
        returned as plain data, so render paths (``Serving.health``)
        never format while holding the gate lock (FL-LOCK002)."""
        with self._cv:
            return {
                "capacity_bytes": self.capacity,
                "inflight_bytes": self._inflight,
                "waiters": len(self._heap),
                "virtual_time": self._vtime,
            }


class _DeviceGate:
    """Virtual-time WFQ over DECODE time — the second metered resource.

    Storage bytes are not the only thing tenants contend for: a tenant
    whose working set is 100% cache-hot never touches the byte gate,
    yet every probe it issues burns decode-engine time (host decode on
    the serving faces, fused launches on the device leg).  This gate
    arbitrates ``lanes`` concurrent decode slots in weighted virtual-
    time order, where a tenant's virtual finish advances by
    ``seconds / weight`` — so under contention, engine time interleaves
    in weight proportion exactly like storage bytes do, and the
    cache-hot tenant queues like everyone else.

    A slot is acquired with an ESTIMATE (the tenant's EWMA of its own
    recent decode walls — nobody knows a decode's cost before running
    it) and the tenant's clock is corrected to the ACTUAL seconds at
    release, so estimation error never accumulates into unfairness.
    ``serve.device_waits`` counts contended acquires;
    ``serve.device_wait_seconds`` is the grant-wait histogram;
    ``serve.device_seconds`` (per-tenant, on the ambient tracer) is the
    fairness ledger benches compare against WFQ-ideal shares."""

    def __init__(self, lanes: int = 1):
        if lanes <= 0:
            raise ValueError(f"lanes must be > 0, got {lanes}")
        self.lanes = int(lanes)
        self._cv = threading.Condition()
        self._busy = 0
        self._vtime = 0.0
        self._heap: list = []   # (vtag, seq, ticket)
        self._seq = 0

    def acquire(self, state: "_TenantShare") -> tuple:
        """Block until granted a lane in virtual-time order; returns the
        lease ``(state, vtag, estimate_s)`` to pass to :meth:`release`.
        """
        with self._cv:
            est = max(state.device_estimate_s, 1e-6)
            vtag = max(self._vtime, state.dfinish)
            state.dfinish = vtag + est / state.weight
            if not self._heap and self._busy < self.lanes:
                self._busy += 1
                self._vtime = max(self._vtime, vtag)
                return (state, vtag, est)
            trace.count("serve.device_waits")
            t_wait = time.perf_counter()
            ticket = [False]
            self._seq += 1
            heapq.heappush(self._heap, (vtag, self._seq, ticket))
            while True:
                if self._pump():
                    self._cv.notify_all()
                if ticket[0]:
                    trace.observe(
                        "serve.device_wait_seconds",
                        time.perf_counter() - t_wait,
                    )
                    return (state, vtag, est)
                self._cv.wait()

    def _pump(self) -> int:
        granted = 0
        while self._heap and self._busy < self.lanes:
            vtag, _seq, ticket = heapq.heappop(self._heap)
            self._busy += 1
            self._vtime = max(self._vtime, vtag)
            ticket[0] = True
            granted += 1
        return granted

    def release(self, lease: tuple, actual_s: float) -> None:
        state, vtag, est = lease
        with self._cv:
            self._busy -= 1
            # charge truth, not the guess: the tenant's clock moves by
            # actual/weight (the estimate only ordered the arrival)
            state.dfinish += (float(actual_s) - est) / state.weight
            if state.dfinish < vtag:
                state.dfinish = vtag
            # fold the actual into the tenant's estimator (EWMA)
            state.device_estimate_s = (
                0.75 * state.device_estimate_s + 0.25 * float(actual_s)
            )
            self._pump()
            self._cv.notify_all()

    def charge(self, state: "_TenantShare", seconds: float) -> None:
        """Post-hoc charge (no lane held): advance the tenant's
        virtual clock by ``seconds / weight`` from the later of the
        gate's clock and its own finish — the SAME clock law acquire
        uses, kept here so the WFQ arithmetic has one home."""
        with self._cv:
            state.dfinish = (
                max(self._vtime, state.dfinish)
                + float(seconds) / state.weight
            )

    def stats(self) -> dict:
        """Snapshot under the cv, formatted outside (FL-LOCK002)."""
        with self._cv:
            return {
                "lanes": self.lanes,
                "busy": self._busy,
                "waiters": len(self._heap),
                "virtual_time": self._vtime,
            }


class _TenantShare:
    """The gate-side state of one tenant: virtual finish times for BOTH
    metered resources (storage bytes, device seconds) + weight.  Bound
    into every :class:`CachedSource` the tenant opens."""

    __slots__ = ("weight", "vfinish", "gate", "dfinish",
                 "device_estimate_s", "device_gate")

    def __init__(self, weight: float, gate: _FairGate,
                 device_gate: Optional[_DeviceGate] = None):
        self.weight = float(weight)
        self.vfinish = 0.0
        self.gate = gate
        self.dfinish = 0.0
        self.device_estimate_s = 0.002   # until the EWMA learns better
        self.device_gate = device_gate

    def acquire(self, cost: int) -> None:
        self.gate.acquire(self, cost)

    def release(self, cost: int) -> None:
        self.gate.release(cost)


class Tenant:
    """One registered serving client — see module docstring.  Created
    via :meth:`Serving.tenant`, closed via :meth:`close` (deregisters
    the weight; the tracer and its report survive for post-mortems)."""

    def __init__(self, serving: "Serving", name: str, weight: float):
        self._serving = serving
        self.name = name
        self.weight = float(weight)
        self.tracer = trace.Tracer(enabled=True)
        # every engine ship/launch span recorded under this tenant's
        # scope bills the device-time WFQ ledger automatically — a
        # sharded/multi-chip scan run via tenant.scanner() needs no
        # explicit metering calls (trace._Span wires the hook through)
        self.tracer.device_charge = self.charge_device
        self._share = _TenantShare(self.weight, serving._gate,
                                   serving._device_gate)
        self._closed = False

    # -- budget admission ---------------------------------------------------

    def prefetch_share(self) -> int:
        """This tenant's slice of the global prefetch budget:
        ``total * weight / Σ open-tenant weights`` (floored at 1 MiB so
        a feather-weight tenant still makes progress)."""
        return self._serving._share_bytes(self.weight)

    def scan_options(self, base: Optional["object"] = None):
        """``base`` (a :class:`~parquet_floor_tpu.scan.ScanOptions`, or
        None for defaults) with ``prefetch_bytes`` replaced by this
        tenant's fair share — the admission knob every scan face already
        obeys."""
        from ..scan import ScanOptions

        sc = base if base is not None else ScanOptions()
        return replace(sc, prefetch_bytes=self.prefetch_share())

    # -- sources ------------------------------------------------------------

    def source_factories(self, sources: Sequence) -> list:
        """Zero-arg factories producing shared-cache-backed sources for
        the scan chain (the scanner resolves factories at file-open time
        and owns the close).  Accepts paths, zero-arg factories, or open
        positional sources (ownership transfers to the scan)."""
        cache = self._serving.cache
        share = self._share

        def make(src):
            def factory():
                inner = src
                if callable(inner) and not hasattr(inner, "read_at"):
                    inner = inner()
                if not hasattr(inner, "read_at"):
                    inner = FileSource(inner)
                try:
                    return CachedSource(inner, cache, gate=share)
                except BaseException:
                    inner.close()
                    raise
            return factory

        return [make(s) for s in sources]

    # -- the scan face ------------------------------------------------------

    def scan(self, sources: Sequence, columns=None, options=None,
             scan=None, predicate=None, order=None):
        """A :class:`~parquet_floor_tpu.scan.DatasetScanner` over
        ``sources``, attributed to this tenant: shared-cache-backed
        sources, fair-share-gated storage reads, ``prefetch_bytes``
        replaced by the tenant's budget share, and the scanner pinned to
        the tenant's tracer — iterate it from anywhere and the metrics
        still land here.  Use under ``with`` (or ``close()``) like any
        scanner."""
        if self._closed:
            raise ValueError(f"tenant {self.name!r} is closed")
        from ..scan import DatasetScanner

        sources = list(sources)
        sc = self.scan_options(scan)
        with trace.using(self.tracer):
            trace.decision("serve.admission", {
                "tenant": self.name,
                "weight": self.weight,
                "prefetch_bytes": sc.prefetch_bytes,
                "files": len(sources),
            })
            return DatasetScanner(
                self.source_factories(sources), columns=columns,
                options=options, scan=sc, predicate=predicate, order=order,
            )

    # -- device-time metering ------------------------------------------------

    @contextlib.contextmanager
    def device_session(self):
        """One metered slice of decode-engine time: acquires a lane
        from the serving context's device WFQ gate (queueing in
        weighted virtual-time order under contention), measures the
        enclosed wall, charges it to this tenant's virtual clock at
        release, and records it in the tenant-attributed
        ``serve.device_seconds`` histogram — the ledger fairness
        benches compare against ideal WFQ shares.  The serving faces
        (lookup/range/aggregate probes, the daemon) wrap each row
        group's decode in one of these.

        The tracer's automatic span-level ``device_charge`` hook is
        SUSPENDED for the session's duration: the lane release charges
        the whole measured wall, so letting the enclosed ship/launch
        spans also bill would double-count them."""
        # attribution is pinned to THIS tenant's tracer (idempotent
        # when the probe faces already activated it), so the fairness
        # ledger and the wait counters land on the right tenant even
        # from a bare device_session() call
        with trace.using(self.tracer):
            lease = self._share.device_gate.acquire(self._share)
        prev_hook = self.tracer.device_charge
        self.tracer.device_charge = None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            actual = time.perf_counter() - t0
            self.tracer.device_charge = prev_hook
            self._share.device_gate.release(lease, actual)
            with trace.using(self.tracer):
                trace.observe("serve.device_seconds", actual)

    def charge_device(self, seconds: float) -> None:
        """Post-hoc device-time charge (no lane held): advance this
        tenant's device virtual clock by ``seconds / weight``.  The
        hook for externally-timed engine work — e.g. a device scan
        leg's fused-launch walls — so that work still pushes the
        tenant back in the WFQ order its next probe queues under."""
        self._share.device_gate.charge(self._share, seconds)
        with trace.using(self.tracer):
            trace.observe("serve.device_seconds", float(seconds))

    # -- observability -------------------------------------------------------

    def report(self, wall_seconds: Optional[float] = None):
        """This tenant's :class:`~parquet_floor_tpu.utils.trace.
        ScanReport` — disjoint from every other tenant's by construction
        (each tenant's scans bind their workers to its own tracer)."""
        return self.tracer.scan_report(
            wall_seconds=wall_seconds,
            budget_bytes=self.prefetch_share(),
        )

    def reset(self) -> None:
        """Clear the tenant's tracer (per-interval reporting)."""
        self.tracer.reset()

    def close(self) -> None:
        """Deregister from the serving context (its weight leaves the
        budget split); idempotent.  The tracer stays readable."""
        if not self._closed:
            self._closed = True
            self._serving._drop(self.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Serving:
    """The per-process serving context: one shared cache, one global
    prefetch budget split across tenants by weight, one weighted-fair
    gate over storage reads.

    ``cache=None`` builds a private :class:`SharedBufferCache` (closed
    with the context); passing one shares it — the caller keeps
    ownership.  ``prefetch_bytes`` is the GLOBAL in-flight budget the
    tenants' shares sum to; ``inflight_bytes`` caps concurrently
    in-flight STORAGE reads for the fair gate (defaults to
    ``prefetch_bytes``)."""

    def __init__(self, cache: Optional[SharedBufferCache] = None,
                 prefetch_bytes: int = 64 << 20,
                 inflight_bytes: Optional[int] = None,
                 device_lanes: int = 2):
        if prefetch_bytes <= 0:
            raise ValueError(
                f"prefetch_bytes must be > 0, got {prefetch_bytes}"
            )
        self._own_cache = cache is None
        self.cache = cache if cache is not None else SharedBufferCache()
        self.prefetch_bytes = int(prefetch_bytes)
        self._gate = _FairGate(
            inflight_bytes if inflight_bytes is not None else prefetch_bytes
        )
        # decode-engine WFQ (docs/serving.md): ``device_lanes``
        # concurrent decode slots, granted in weighted virtual-time
        # order — the resource a cache-hot tenant still consumes
        self._device_gate = _DeviceGate(device_lanes)
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        self._slos: Dict[str, "object"] = {}   # tenant name -> SloMonitor
        # attach-time cumulative (histogram, errors) baselines: what
        # check_slos subtracts so pre-monitoring traffic never breaches
        self._slo_base: Dict[str, tuple] = {}
        self._closed = False

    def tenant(self, name: str, weight: float = 1.0) -> Tenant:
        """Register (or fetch) the tenant ``name``.  Re-requesting an
        open tenant returns the existing object — one identity per name;
        a different weight on a re-request is rejected rather than
        silently rewriting the share."""
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        with self._lock:
            if self._closed:
                raise ValueError("Serving context is closed")
            t = self._tenants.get(name)
            if t is not None:
                if t.weight != float(weight):
                    raise ValueError(
                        f"tenant {name!r} is already registered with "
                        f"weight {t.weight}, not {weight}"
                    )
                return t
            t = Tenant(self, name, weight)
            self._tenants[name] = t
        with trace.using(t.tracer):
            trace.decision("serve.tenant", {
                "tenant": name, "weight": float(weight),
            })
        return t

    def tenants(self) -> list:
        with self._lock:
            return list(self._tenants.values())

    def _share_bytes(self, weight: float) -> int:
        with self._lock:
            total_w = sum(t.weight for t in self._tenants.values())
        return self._share_from_total(weight, total_w)

    def _share_from_total(self, weight: float, total_w: float) -> int:
        """The granted share given a pre-summed weight total — ONE
        formula (1 MiB floor included) for admission and every render
        path, so the health page can never disagree with the grant."""
        total_w = total_w or weight
        return max(1 << 20, int(self.prefetch_bytes * weight / total_w))

    # -- SLO monitoring ------------------------------------------------------

    def set_slo(self, name: str, target,
                histogram_name: str = "serve.lookup_seconds"):
        """Attach an :class:`~parquet_floor_tpu.serve.slo.SloTarget` to
        tenant ``name`` (which must be registered); returns the
        :class:`~parquet_floor_tpu.serve.slo.SloMonitor`.  Re-setting
        replaces the monitor (fresh windows).  The tenant's CURRENT
        cumulative histogram/error counters become the monitor's
        baseline — only traffic AFTER the attach can breach (historic
        slow probes from before monitoring was wanted must not fire a
        page on the first tick)."""
        from .slo import SloMonitor, tenant_errors

        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise ValueError(f"tenant {name!r} is not registered")
        # baseline snapshots come off the tenant tracer OUTSIDE the
        # serving lock (its own lock suffices); captured BEFORE the
        # monitor registers, so any racing traffic lands on the "new"
        # side of the subtraction
        base = (
            tenant.tracer.histograms().get(histogram_name),
            tenant_errors(tenant.tracer.counters()),
        )
        mon = SloMonitor(name, target, histogram_name=histogram_name)
        with self._lock:
            if name not in self._tenants:
                raise ValueError(f"tenant {name!r} is not registered")
            self._slos[name] = mon
            self._slo_base[name] = base
        return mon

    def check_slos(self, now: Optional[float] = None) -> Dict[str, "object"]:
        """One monitoring tick: snapshot every monitored tenant's
        latency histogram + error counters into its monitor, evaluate,
        and emit a registered ``serve.slo_breach`` decision ON THE
        BREACHING TENANT'S tracer (so the alert is attributed exactly
        like the metrics that caused it).  Returns tenant name →
        :class:`~parquet_floor_tpu.serve.slo.SloStatus`."""
        from .slo import tenant_errors

        with self._lock:
            monitored = [
                (self._tenants[n], m, self._slo_base.get(n, (None, 0)))
                for n, m in self._slos.items()
                if n in self._tenants
            ]
        out: Dict[str, "object"] = {}
        for tenant, mon, (base_hist, base_errors) in monitored:
            hist = tenant.tracer.histograms().get(mon.histogram_name)
            errors = tenant_errors(tenant.tracer.counters())
            if base_hist is not None:
                hist = (
                    hist.subtract(base_hist) if hist is not None
                    else None
                )
            errors = max(0, errors - base_errors)
            mon.observe(hist, errors=errors, now=now)
            status = mon.evaluate(now=now)
            out[tenant.name] = status
            if status.breach:
                with trace.using(tenant.tracer):
                    trace.decision("serve.slo_breach", {
                        "tenant": tenant.name,
                        "p99_ms": (
                            None if status.p99_seconds is None
                            else round(status.p99_seconds * 1e3, 3)
                        ),
                        "bound_ms": round(
                            mon.target.p99_seconds * 1e3, 3
                        ),
                        "fast_burn": round(status.fast_burn, 2),
                        "slow_burn": round(status.slow_burn, 2),
                        "error_breach": status.error_breach,
                    })
                trace.flight_fire("slo_breach", {
                    "tenant": tenant.name,
                    "fast_burn": round(status.fast_burn, 2),
                    "slow_burn": round(status.slow_burn, 2),
                    "error_breach": status.error_breach,
                })
        return out

    def health(self, now: Optional[float] = None) -> str:
        """The one-page serving summary: cache tiers, fair-gate
        pressure, and per-tenant traffic / latency quantiles / SLO
        state.  Runs a :meth:`check_slos` tick first, then renders.

        Lock discipline (FL-LOCK002, pinned by test): every shared
        structure is SNAPSHOTTED under its own lock into plain data —
        tenant list under ``Serving._lock``, gate pressure via
        ``_FairGate.stats()`` under the gate cv, tracer state under
        each tracer's lock — and ALL formatting happens outside, so a
        slow render can never stall admission or storage grants."""
        statuses = self.check_slos(now=now)
        with self._lock:
            tenants = list(self._tenants.values())
            total_w = sum(t.weight for t in tenants)
        gate = self._gate.stats()            # snapshot under the cv
        dgate = self._device_gate.stats()    # snapshot under its cv
        cache = self.cache.stats()           # snapshot under its lock
        rows = []
        for t in sorted(tenants, key=lambda t: t.name):
            counters = t.tracer.counters()
            hists = t.tracer.histograms()
            hit = counters.get("serve.cache_hit_bytes", 0)
            miss = counters.get("serve.cache_miss_bytes", 0)
            dev = hists.get("serve.device_seconds")
            rows.append({
                "device_seconds": (
                    round(dev.total, 4) if dev is not None else None
                ),
                "name": t.name,
                "weight": t.weight,
                # the REAL granted share (the admission formula, 1 MiB
                # floor included) off the one weight total snapshotted
                # above — no per-row lock round-trips
                "share": self._share_from_total(t.weight, total_w),
                "probes": counters.get("serve.lookup_probes", 0),
                "hit_rate": (hit / (hit + miss)) if hit + miss else None,
                "lookup": hists.get("serve.lookup_seconds"),
                "fair_wait": hists.get("serve.fair_wait_seconds"),
                "status": statuses.get(t.name),
            })
        # -- snapshots complete: pure formatting from here on --------------
        lines = [
            "serving health:",
            (
                f"  cache             {cache['hit_bytes']} B hit /"
                f" {cache['miss_bytes']} B miss,"
                f" {cache['data_bytes_used']} B data"
                f" + {cache['meta_bytes_used']} B pinned,"
                f" {cache['files']} file(s)"
            ),
            (
                f"  fair gate         {gate['inflight_bytes']}/"
                f"{gate['capacity_bytes']} B in flight,"
                f" {gate['waiters']} waiter(s)"
            ),
            (
                f"  device gate       {dgate['busy']}/{dgate['lanes']}"
                f" lane(s) busy, {dgate['waiters']} waiter(s)"
            ),
        ]
        if not rows:
            lines.append("  (no tenants registered)")
        for r in rows:
            hr = ("n/a" if r["hit_rate"] is None
                  else f"{r['hit_rate'] * 100:.1f}%")
            dv = ("" if r["device_seconds"] is None
                  else f" device={r['device_seconds']:g}s")
            lines.append(
                f"  tenant {r['name']:<12} weight={r['weight']:g}"
                f" share={int(r['share'])} B"
                f" probes={r['probes']} hit-rate={hr}{dv}"
            )
            if r["lookup"] is not None:
                lines.append(f"    lookup          {r['lookup'].render()}")
            if r["fair_wait"] is not None:
                lines.append(
                    f"    fair wait       {r['fair_wait'].render()}"
                )
            if r["status"] is not None:
                lines.append(f"    slo             {r['status'].render()}")
        return "\n".join(lines)

    def _drop(self, name: str) -> None:
        with self._lock:
            self._tenants.pop(name, None)
            self._slos.pop(name, None)
            self._slo_base.pop(name, None)

    def close(self) -> None:
        """Close every tenant and (when owned) the cache; idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            tenants = list(self._tenants.values())
            self._tenants.clear()
        for t in tenants:
            t._closed = True
        if self._own_cache:
            self.cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
