"""Cross-process cache tier over ``multiprocessing.shared_memory``.

PR 9's :class:`~parquet_floor_tpu.serve.cache.SharedBufferCache` is
process-wide; production serving is N worker processes per host, and
each of them duplicating the cache multiplies both the memory AND the
storage reads N-fold — the single-flight law stopped at the process
boundary.  :class:`ShmCacheTier` is the shared tier below every
worker's in-process cache:

* **one segment, two rings** — a ``data`` ring and a ``meta`` ring
  (the pinned-metadata law: footer/page-index/bloom/dictionary bytes
  have their own budget, so data churn never evicts them) carved out of
  one ``SharedMemory`` segment, each a log-structured ring heap whose
  eviction is counted, never silent.  Eviction is SECOND-CHANCE
  (LRU-grade): lookups stamp the slot, and the eviction pass rescues a
  stamped tail record to the ring's head (stamp cleared,
  ``serve.shm_rescues``) instead of dropping it, so a hot range
  survives a churn of cold inserts;
* **exact-range keying** — entries are keyed by a 128-bit digest of
  ``(file key, offset, length)``.  Every worker runs the same planner,
  so identical requests dedupe across processes; *containment* lookups
  (a sub-range of a cached extent) are the in-process L1's job —
  :class:`~parquet_floor_tpu.serve.cache.SharedBufferCache` sits above
  this tier and keeps that law;
* **cross-process single-flight** — a fixed flight table in the
  segment: the first process to miss a range registers a *lease* and
  leads the storage read; concurrent processes (and threads) requesting
  the same range poll for the leader's bytes instead of re-issuing the
  read (``serve.shm_singleflight_waits``).  A leader that dies or
  stalls past its lease is *taken over* (``serve.shm_takeovers``): a
  waiter claims the flight and re-issues — the cross-process analogue
  of "a failed leader clears the flight so retries re-issue cleanly"
  (an exception cannot propagate across processes, so re-leading IS the
  propagation);
* **eviction-safe borrows** — readers copy payload bytes OUT of the
  segment under the lock, so eviction (which may overwrite ring bytes)
  can never corrupt a borrowed buffer, only forget the entry.  This is
  the same law as the in-process tier, met by copy-out instead of
  immutable views (a view into a mutable shared ring would be exactly
  the corruption the law forbids).

Mutual exclusion is ``fcntl.flock`` on a sidecar lock file (works
between unrelated processes — workers need not be fork children) under
a per-process ``threading.Lock`` (flock is per-open-file-description,
so threads of one process must serialize around it themselves).  All
storage I/O and all polling sleeps happen OUTSIDE the lock.

Attach with :meth:`ShmCacheTier.attach` from worker processes; the
creating process owns the segment and unlinks it on close.  Stats live
in the segment header, so :meth:`stats` is the cross-process truth the
multi-process smoke asserts.  Docs: ``docs/serving.md``.
"""

from __future__ import annotations

import contextlib
import fcntl
import hashlib
import os
import struct
import tempfile
import threading
import time
from multiprocessing import shared_memory
from typing import List, Optional, Sequence, Tuple

from ..utils import trace

_MAGIC = b"PFTPUSH1"
_VERSION = 2   # v2: slot access stamps + second-chance eviction

# header field layout (struct offsets into the segment)
_H_MAGIC = 0           # 8s
_H_VERSION = 8         # <I
_H_SLOTS = 12          # <I
_H_FLIGHTS = 16        # <I
_H_DATA_CAP = 24       # <Q
_H_META_CAP = 32       # <Q
_H_RING = 40           # 4 x <Q: data_head, data_tail, meta_head, meta_tail
_H_STATS = 72          # _N_STATS x <Q
_STAT_NAMES = (
    "hits", "misses", "hit_bytes", "miss_bytes",
    "evictions", "meta_evictions", "singleflight_waits", "takeovers",
    "rescues",
)
_N_STATS = len(_STAT_NAMES)
_HEADER_BYTES = 256

_FLIGHT_REC = 32       # d0 u64 | d1 u64 | deadline f64 | state u32 | pad
_SLOT_REC = 40         # d0 u64 | d1 u64 | ring u32 | pad | off u64 | len u64

_RING_DATA = 1
_RING_META = 2
_SKIP_SLOT = 0xFFFFFFFF

# waiter poll cadence: start fine (a page-sized local read completes in
# well under a millisecond), back off toward 5 ms so a long remote read
# does not spin a waiting worker
_POLL_MIN_S = 0.0005
_POLL_MAX_S = 0.005


def _digest(key: tuple, offset: int, length: int) -> Tuple[int, int]:
    """128-bit identity of one exact range of one file.  The key tuple
    is the in-process cache's ``source_key`` — ``(name, size)`` — so
    two workers opening the same path at the same size share entries."""
    canon = "\x1f".join(
        [str(part) for part in key] + [str(int(offset)), str(int(length))]
    ).encode("utf-8", "surrogateescape")
    d = hashlib.blake2b(canon, digest_size=16).digest()
    # bias away from the all-zero digest: (0, 0) marks a free slot
    d0 = int.from_bytes(d[:8], "little") | 1
    return d0, int.from_bytes(d[8:], "little")


def _ceil8(n: int) -> int:
    return (int(n) + 7) & ~7


class ShmCacheTier:
    """The cross-process byte tier (module docstring).  Create once per
    host (``ShmCacheTier.create``), attach from every worker
    (``ShmCacheTier.attach(name)``), drop into each worker's in-process
    cache via ``SharedBufferCache(shm=tier)``."""

    def __init__(self, *, data_bytes: int = 64 << 20,
                 meta_bytes: int = 16 << 20, slots: int = 4096,
                 flights: int = 256, lease_s: float = 10.0,
                 _attach_name: Optional[str] = None):
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0, got {lease_s}")
        self.lease_s = float(lease_s)
        self._tlock = threading.Lock()
        self._closed = False
        self._created = _attach_name is None
        if _attach_name is None:
            data_bytes = _ceil8(data_bytes)
            meta_bytes = _ceil8(meta_bytes)
            if data_bytes <= 0 or meta_bytes <= 0:
                raise ValueError("tier budgets must be > 0")
            if slots <= 0 or flights <= 0:
                raise ValueError("slots and flights must be > 0")
            total = (_HEADER_BYTES + flights * _FLIGHT_REC
                     + slots * _SLOT_REC + data_bytes + meta_bytes)
            self._shm = shared_memory.SharedMemory(create=True, size=total)
            buf = self._shm.buf
            buf[:_HEADER_BYTES] = b"\x00" * _HEADER_BYTES
            struct.pack_into("8s", buf, _H_MAGIC, _MAGIC)
            struct.pack_into("<I", buf, _H_VERSION, _VERSION)
            struct.pack_into("<I", buf, _H_SLOTS, int(slots))
            struct.pack_into("<I", buf, _H_FLIGHTS, int(flights))
            struct.pack_into("<Q", buf, _H_DATA_CAP, data_bytes)
            struct.pack_into("<Q", buf, _H_META_CAP, meta_bytes)
            zero_span = flights * _FLIGHT_REC + slots * _SLOT_REC
            buf[_HEADER_BYTES:_HEADER_BYTES + zero_span] = b"\x00" * zero_span
        else:
            self._shm = shared_memory.SharedMemory(name=_attach_name)
            # Python <3.13 registers every ATTACH with the resource
            # tracker, which unlinks the segment when the attaching
            # process exits — destroying it under the creator.  The
            # creator keeps its registration (it owns the unlink).
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(self._shm._name,
                                            "shared_memory")
            except Exception:   # pragma: no cover - platform-dependent
                pass
            buf = self._shm.buf
            magic, = struct.unpack_from("8s", buf, _H_MAGIC)
            version, = struct.unpack_from("<I", buf, _H_VERSION)
            if magic != _MAGIC or version != _VERSION:
                self._shm.close()
                raise ValueError(
                    f"segment {_attach_name!r} is not a ShmCacheTier "
                    f"(magic {magic!r}, version {version})"
                )
        buf = self._shm.buf
        self.slot_count, = struct.unpack_from("<I", buf, _H_SLOTS)
        self.flight_count, = struct.unpack_from("<I", buf, _H_FLIGHTS)
        self.data_bytes, = struct.unpack_from("<Q", buf, _H_DATA_CAP)
        self.meta_bytes, = struct.unpack_from("<Q", buf, _H_META_CAP)
        self._flights_off = _HEADER_BYTES
        self._slots_off = self._flights_off + self.flight_count * _FLIGHT_REC
        self._data_off = self._slots_off + self.slot_count * _SLOT_REC
        self._meta_off = self._data_off + self.data_bytes
        import numpy as np

        slot_dt = np.dtype([
            ("d0", "<u8"), ("d1", "<u8"), ("ring", "<u4"), ("pad", "<u4"),
            ("off", "<u8"), ("len", "<u8"),
        ])
        flight_dt = np.dtype([
            ("d0", "<u8"), ("d1", "<u8"), ("deadline", "<f8"),
            ("state", "<u4"), ("pad", "<u4"),
        ])
        self._slots = np.frombuffer(
            buf, dtype=slot_dt, count=self.slot_count,
            offset=self._slots_off,
        )
        self._flights = np.frombuffer(
            buf, dtype=flight_dt, count=self.flight_count,
            offset=self._flights_off,
        )
        # the sidecar lock file: flock works between unrelated processes
        self._lock_path = os.path.join(
            tempfile.gettempdir(), f"pftpu-shm-{self._shm.name}.lock"
        )
        self._lock_fd = os.open(self._lock_path,
                                os.O_CREAT | os.O_RDWR, 0o600)

    # -- construction faces --------------------------------------------------

    @classmethod
    def create(cls, data_bytes: int = 64 << 20, meta_bytes: int = 16 << 20,
               slots: int = 4096, flights: int = 256,
               lease_s: float = 10.0) -> "ShmCacheTier":
        """A fresh segment, owned (and unlinked at close) by the caller."""
        return cls(data_bytes=data_bytes, meta_bytes=meta_bytes,
                   slots=slots, flights=flights, lease_s=lease_s)

    @classmethod
    def attach(cls, name: str, lease_s: float = 10.0) -> "ShmCacheTier":
        """Attach a worker process to an existing segment by name."""
        return cls(lease_s=lease_s, _attach_name=name)

    @property
    def name(self) -> str:
        """The segment name workers pass to :meth:`attach`."""
        return self._shm.name

    # -- locking -------------------------------------------------------------

    @contextlib.contextmanager
    def _locked(self):
        """tlock (threads of this process) then flock (other
        processes); storage I/O and polling sleeps stay OUTSIDE."""
        with self._tlock:
            if self._closed:
                raise ValueError("ShmCacheTier is closed")
            fcntl.flock(self._lock_fd, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._lock_fd, fcntl.LOCK_UN)

    # -- header state (caller holds the lock) --------------------------------

    def _ring_state(self) -> list:
        return list(struct.unpack_from("<4Q", self._shm.buf, _H_RING))

    def _set_ring_state(self, st: Sequence[int]) -> None:
        struct.pack_into("<4Q", self._shm.buf, _H_RING, *st)

    def _bump(self, stat: str, n: int = 1) -> None:
        i = _STAT_NAMES.index(stat)
        off = _H_STATS + 8 * i
        v, = struct.unpack_from("<Q", self._shm.buf, off)
        struct.pack_into("<Q", self._shm.buf, off, v + n)

    # -- the ring heaps (caller holds the lock) ------------------------------

    def _heap_span(self, ring: int) -> Tuple[int, int]:
        if ring == _RING_META:
            return self._meta_off, self.meta_bytes
        return self._data_off, self.data_bytes

    def _evict_tail(self, ring: int, st: list, rescue: bool = True) -> None:
        """Retire the record at the ring's tail (and its slot) — with a
        SECOND CHANCE: a tail record whose slot carries an access stamp
        (``pad`` set by :meth:`_lookup_locked` since insertion) is
        rescued to the ring's head with the stamp cleared instead of
        evicted, so a hot range survives a churn of cold inserts
        (LRU-grade behavior on a log-structured ring).  Termination:
        the stamp is cleared on rescue under the held lock, so each
        live record is rescued at most once per eviction pass before
        the eviction is real."""
        base, cap = self._heap_span(ring)
        hi, ti = (0, 1) if ring == _RING_DATA else (2, 3)
        tail = st[ti]
        pos = base + (tail % cap)
        rec_len, slot_idx = struct.unpack_from("<II", self._shm.buf, pos)
        if rec_len < 8 or tail + rec_len > st[hi]:
            # a torn ring (should be unreachable under the lock) —
            # resynchronize by dropping the whole ring, slots included
            # (a leaked slot over reclaimed ring bytes would serve
            # WRONG bytes; forgetting everything is always safe)
            import numpy as np

            stale = np.flatnonzero(self._slots["ring"] == ring)
            for i in stale:
                self._slots[int(i)]["ring"] = 0
            st[ti] = st[hi]
            return
        if slot_idx != _SKIP_SLOT and slot_idx < self.slot_count:
            s = self._slots[slot_idx]
            if int(s["ring"]) == ring and int(s["off"]) == tail + 8:
                if rescue and int(s["pad"]) != 0:
                    data = bytes(self._shm.buf[pos + 8:pos + 8
                                               + int(s["len"])])
                    d0, d1 = int(s["d0"]), int(s["d1"])
                    self._slots[slot_idx]["ring"] = 0
                    st[ti] = tail + rec_len
                    self._reinsert_head(ring, st, slot_idx, d0, d1, data)
                    self._bump("rescues")
                    trace.count("serve.shm_rescues")
                    return
                self._slots[slot_idx]["ring"] = 0
                if ring == _RING_META:
                    self._bump("meta_evictions")
                    trace.count("serve.shm_meta_evictions")
                else:
                    self._bump("evictions")
                    trace.count("serve.shm_evictions")
        st[ti] = tail + rec_len

    def _reinsert_head(self, ring: int, st: list, slot: int, d0: int,
                       d1: int, data: bytes) -> None:
        """Re-install a rescued record at the ring's head, stamp
        cleared, reusing the slot its rescue just freed.  Space is made
        with NO further rescues (``rescue=False``), so a rescue can
        never recurse into another rescue."""
        base, cap = self._heap_span(ring)
        hi, ti = (0, 1) if ring == _RING_DATA else (2, 3)
        need = 8 + _ceil8(len(data))
        rem = cap - (st[hi] % cap)
        if rem < need:
            while (st[hi] + rem) - st[ti] > cap:
                self._evict_tail(ring, st, rescue=False)
            pos = base + (st[hi] % cap)
            struct.pack_into("<II", self._shm.buf, pos, rem, _SKIP_SLOT)
            st[hi] += rem
        while (st[hi] + need) - st[ti] > cap:
            self._evict_tail(ring, st, rescue=False)
        pos = base + (st[hi] % cap)
        struct.pack_into("<II", self._shm.buf, pos, need, slot)
        self._shm.buf[pos + 8:pos + 8 + len(data)] = data
        rec = self._slots[slot]
        rec["d0"] = d0
        rec["d1"] = d1
        rec["ring"] = ring
        rec["pad"] = 0
        rec["off"] = st[hi] + 8
        rec["len"] = len(data)
        st[hi] += need

    def _free_slot(self, st: list) -> Optional[int]:
        import numpy as np

        free = np.flatnonzero(self._slots["ring"] == 0)
        if free.size:
            return int(free[0])
        # the slot table is the entry count bound: evicting one ring
        # record frees exactly one slot
        for ring in (_RING_DATA, _RING_META):
            hi, ti = (0, 1) if ring == _RING_DATA else (2, 3)
            while st[ti] < st[hi]:
                self._evict_tail(ring, st)
                free = np.flatnonzero(self._slots["ring"] == 0)
                if free.size:
                    return int(free[0])
        return None

    def _insert_locked(self, d0: int, d1: int, data: bytes,
                       pinned: bool) -> None:
        ring = _RING_META if pinned else _RING_DATA
        base, cap = self._heap_span(ring)
        need = 8 + _ceil8(len(data))
        if need > cap:
            return   # larger than the whole ring: serve-through, uncached
        st = self._ring_state()
        slot = self._free_slot(st)
        if slot is None:   # pragma: no cover - slots >= 1 frees above
            self._set_ring_state(st)
            return
        hi, ti = (0, 1) if ring == _RING_DATA else (2, 3)
        # contiguity: a record never wraps — skip-pad to the boundary
        rem = cap - (st[hi] % cap)
        if rem < need:
            while (st[hi] + rem) - st[ti] > cap:
                self._evict_tail(ring, st)
            pos = base + (st[hi] % cap)
            struct.pack_into("<II", self._shm.buf, pos, rem, _SKIP_SLOT)
            st[hi] += rem
        while (st[hi] + need) - st[ti] > cap:
            self._evict_tail(ring, st)
        pos = base + (st[hi] % cap)
        struct.pack_into("<II", self._shm.buf, pos, need, slot)
        self._shm.buf[pos + 8:pos + 8 + len(data)] = data
        rec = self._slots[slot]
        rec["d0"] = d0
        rec["d1"] = d1
        rec["ring"] = ring
        rec["pad"] = 0   # fresh entries start unstamped (one full lap
        #                  of cold churn evicts an entry never re-read)
        rec["off"] = st[hi] + 8
        rec["len"] = len(data)
        st[hi] += need
        self._set_ring_state(st)

    def _lookup_locked(self, d0: int, d1: int) -> Optional[bytes]:
        import numpy as np

        hit = np.flatnonzero(
            (self._slots["d0"] == d0) & (self._slots["d1"] == d1)
            & (self._slots["ring"] != 0)
        )
        if not hit.size:
            return None
        rec = self._slots[int(hit[0])]
        # access stamp: the eviction pass gives stamped records a
        # second chance (rescue to head) — cross-process LRU-grade
        # behavior for the price of one u32 write under the lock
        rec["pad"] = 1
        base, cap = self._heap_span(int(rec["ring"]))
        pos = base + (int(rec["off"]) % cap)
        # copy-out under the lock: the borrow law (module docstring)
        return bytes(self._shm.buf[pos:pos + int(rec["len"])])

    # -- flights (caller holds the lock) -------------------------------------

    def _flight_check(self, d0: int, d1: int, claim: bool) -> bool:
        """True when another process/thread is already leading this
        range.  With ``claim``, an absent/expired flight is claimed for
        the caller (who must then lead the read and :meth:`_flight_done`
        it)."""
        import numpy as np

        now = time.monotonic()
        live = np.flatnonzero(
            (self._flights["state"] == 1)
            & (self._flights["d0"] == d0) & (self._flights["d1"] == d1)
        )
        for i in live:
            f = self._flights[int(i)]
            if float(f["deadline"]) > now:
                return True
            self._flights[int(i)]["state"] = 0   # expired lease
        if claim:
            free = np.flatnonzero(self._flights["state"] == 0)
            if free.size:
                f = self._flights[int(free[0])]
                f["d0"] = d0
                f["d1"] = d1
                f["deadline"] = now + self.lease_s
                f["state"] = 1
            # a full flight table degrades to an unrecorded lead — a
            # duplicate read is possible then, never a wrong result
        return False

    def _flight_done(self, d0: int, d1: int) -> None:
        import numpy as np

        mine = np.flatnonzero(
            (self._flights["state"] == 1)
            & (self._flights["d0"] == d0) & (self._flights["d1"] == d1)
        )
        for i in mine:
            self._flights[int(i)]["state"] = 0

    # -- public faces --------------------------------------------------------

    def get(self, key: tuple, offset: int, length: int) -> Optional[bytes]:
        """The cached bytes of exactly ``(offset, length)`` of file
        ``key``, or None.  (Exact-range: containment is the L1's job.)"""
        d0, d1 = _digest(key, offset, length)
        with self._locked():
            data = self._lookup_locked(d0, d1)
            if data is not None:
                self._bump("hits")
                self._bump("hit_bytes", len(data))
            return data

    def put(self, key: tuple, offset: int, data, pinned: bool = False
            ) -> None:
        """Install bytes for exactly ``(offset, len(data))``; a range
        already present is not duplicated."""
        data = bytes(data)
        d0, d1 = _digest(key, offset, len(data))
        with self._locked():
            if self._lookup_locked(d0, d1) is None:
                self._insert_locked(d0, d1, data, pinned)

    def read_through(self, key: tuple, ranges: Sequence[Tuple[int, int]],
                     read_many_fn, pinned: bool = False) -> List[bytes]:
        """The tier's single-flight read path, called by the in-process
        cache below its OWN single-flight layer: classify every range as
        shm hit / flight to await / range to lead in one lock pass,
        issue ONE vectored ``read_many_fn`` for the led ranges, install
        them, then poll out the awaited ones (taking over expired
        leases).  Returns one ``bytes`` per input range, in order."""
        ranges = [(int(o), int(n)) for o, n in ranges]
        out: List[Optional[bytes]] = [None] * len(ranges)
        leads: List[int] = []
        waits: List[int] = []
        digests = [_digest(key, o, n) for o, n in ranges]
        with self._locked():
            led_here = set()
            for pos, (d0, d1) in enumerate(digests):
                data = self._lookup_locked(d0, d1)
                if data is not None:
                    self._bump("hits")
                    self._bump("hit_bytes", len(data))
                    trace.count("serve.shm_hits")
                    trace.count("serve.shm_hit_bytes", len(data))
                    out[pos] = data
                    continue
                if (d0, d1) in led_here:
                    # a duplicate range within this very call: our own
                    # lead below installs it; the await loop then finds
                    # it on the first poll
                    waits.append(pos)
                    continue
                if self._flight_check(d0, d1, claim=True):
                    self._bump("singleflight_waits")
                    trace.count("serve.shm_singleflight_waits")
                    waits.append(pos)
                    continue
                led_here.add((d0, d1))
                self._bump("misses")
                self._bump("miss_bytes", ranges[pos][1])
                trace.count("serve.shm_misses")
                trace.count("serve.shm_miss_bytes", ranges[pos][1])
                leads.append(pos)
        if leads:
            try:
                bufs = read_many_fn([ranges[p] for p in leads])
            except BaseException:
                with self._locked():
                    for p in leads:
                        self._flight_done(*digests[p])
                raise
            with self._locked():
                for p, buf in zip(leads, bufs):
                    data = bytes(buf)
                    out[p] = data
                    if self._lookup_locked(*digests[p]) is None:
                        self._insert_locked(*digests[p], data, pinned)
                    self._flight_done(*digests[p])
        for p in waits:
            out[p] = self._await_range(key, ranges[p], digests[p],
                                       read_many_fn, pinned)
        return out   # type: ignore[return-value]

    def _await_range(self, key: tuple, rng: Tuple[int, int],
                     dig: Tuple[int, int], read_many_fn,
                     pinned: bool) -> bytes:
        """Poll for another process's in-flight read of one range; on an
        expired lease, take the flight over and lead it ourselves."""
        t0 = time.perf_counter()
        poll = _POLL_MIN_S
        first = True
        while True:
            if first:
                # check before any sleep: a duplicate range in one
                # call (installed by our own lead) and a cross-process
                # wait that resolved during the lead read are both
                # already present — the hot path must not stall
                first = False
            else:
                time.sleep(poll)
                poll = min(poll * 2, _POLL_MAX_S)
            with self._locked():
                data = self._lookup_locked(*dig)
                if data is not None:
                    self._bump("hits")
                    self._bump("hit_bytes", len(data))
                    trace.observe("serve.shm_wait_seconds",
                                  time.perf_counter() - t0)
                    return data
                if not self._flight_check(*dig, claim=True):
                    # the leader's lease expired (or it failed and
                    # cleared the flight): we are the leader now
                    self._bump("takeovers")
                    trace.count("serve.shm_takeovers")
                    self._bump("misses")
                    self._bump("miss_bytes", rng[1])
                    trace.count("serve.shm_misses")
                    trace.count("serve.shm_miss_bytes", rng[1])
                    break
        try:
            buf = read_many_fn([rng])[0]
        except BaseException:
            with self._locked():
                self._flight_done(*dig)
            raise
        data = bytes(buf)
        with self._locked():
            if self._lookup_locked(*dig) is None:
                self._insert_locked(*dig, data, pinned)
            self._flight_done(*dig)
        trace.observe("serve.shm_wait_seconds", time.perf_counter() - t0)
        return data

    # -- observability / lifecycle -------------------------------------------

    def stats(self) -> dict:
        """The segment header's cross-process truth (all workers'
        traffic folded), plus live occupancy."""
        with self._locked():
            vals = struct.unpack_from(f"<{_N_STATS}Q", self._shm.buf,
                                      _H_STATS)
            st = self._ring_state()
            import numpy as np

            live = int(np.count_nonzero(self._slots["ring"]))
            inflight = int(np.count_nonzero(self._flights["state"]))
        out = dict(zip(_STAT_NAMES, (int(v) for v in vals)))
        out.update({
            "data_bytes_used": st[0] - st[1],
            "meta_bytes_used": st[2] - st[3],
            "entries": live,
            "flights_inflight": inflight,
            "data_bytes": self.data_bytes,
            "meta_bytes": self.meta_bytes,
            "name": self._shm.name,
        })
        return out

    def close(self) -> None:
        """Detach; the creating process also unlinks the segment (and
        its lock file).  Idempotent."""
        with self._tlock:
            if self._closed:
                return
            self._closed = True
            # release the numpy views before closing: SharedMemory
            # refuses to close while buffer exports are alive
            self._slots = None
            self._flights = None
            self._shm.close()
            if self._created:
                try:
                    self._shm.unlink()
                except OSError:   # pragma: no cover - double unlink race
                    pass
                try:
                    os.unlink(self._lock_path)
                except OSError:
                    pass
            os.close(self._lock_fd)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
