"""Per-tenant SLO monitoring: latency/error objectives over sliding
windows with multi-window burn-rate alerting.

A serving deployment does not page on "p99 was high for one second" —
it pages when the **error budget** is burning fast enough that the
monthly objective is in danger (the multi-window multi-burn-rate
pattern; *The Tail at Scale* is why the objective is a tail quantile in
the first place).  The pieces:

* :class:`SloTarget` — the objective: a latency bound that at least
  ``1 - latency_budget`` of requests must beat (``p99_seconds`` with
  the default 1% budget), and an error-rate bound.
* :class:`SloMonitor` — a ring of **cumulative** histogram snapshots
  (the :class:`~parquet_floor_tpu.utils.histogram.LogHistogram` the
  tenant tracers already record via ``Tracer.observe``).  A window's
  traffic is the newest snapshot minus the one at the window's far
  edge (``LogHistogram.subtract`` — the same increase() derivation a
  Prometheus burn-rate query does), so feeding it is one cheap
  ``observe_tenant`` call per tick, no per-request work.
* Burn rate = (fraction of the window's requests over the bound) /
  ``latency_budget``.  An alert needs BOTH the fast window (minutes —
  is it happening now?) and the slow window (the hour — is it real,
  not a blip?) burning past their thresholds, which is what keeps a
  single slow request from paging and a sustained regression from
  hiding.

:meth:`Serving.check_slos <parquet_floor_tpu.serve.tenancy.Serving.
check_slos>` drives monitors from the live tenant tracers and emits a
registered ``serve.slo_breach`` decision ON THE BREACHING TENANT'S
tracer; ``Serving.health()`` renders the one-page summary.  Clocks are
injectable (``now=``) so the window math is deterministically testable.
Docs: ``docs/serving.md`` / ``docs/observability.md``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Tuple

from ..utils.histogram import LogHistogram


@dataclass(frozen=True)
class SloTarget:
    """One tenant's objective.  ``p99_seconds`` is the latency bound
    the ``1 - latency_budget`` quantile must beat (budget 0.01 = a p99
    objective); ``error_rate`` bounds errors/requests over the same
    windows.  The default burn thresholds and windows are the classic
    page-worthy pair (14.4x over 5 min AND 6x over 1 h); tests and
    smokes shrink the windows, not the math."""

    p99_seconds: float
    latency_budget: float = 0.01
    error_rate: float = 0.01
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn: float = 14.4
    slow_burn: float = 6.0

    def __post_init__(self):
        if self.p99_seconds <= 0:
            raise ValueError(
                f"p99_seconds must be > 0, got {self.p99_seconds}"
            )
        if not 0 < self.latency_budget < 1:
            raise ValueError(
                f"latency_budget must be in (0, 1), got "
                f"{self.latency_budget}"
            )
        if self.fast_window_s <= 0 or self.slow_window_s < \
                self.fast_window_s:
            raise ValueError(
                "windows must satisfy 0 < fast_window_s <= slow_window_s"
            )


@dataclass
class SloStatus:
    """One evaluation: burn rates per window, the fast window's
    quantiles, and the breach verdicts."""

    tenant: str
    breach: bool
    latency_breach: bool
    error_breach: bool
    fast_burn: float
    slow_burn: float
    fast_error_burn: float
    slow_error_burn: float
    p50_seconds: Optional[float]
    p99_seconds: Optional[float]
    samples: int                     # requests in the fast window
    target: Optional[SloTarget] = field(repr=False, default=None)

    def as_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "breach": self.breach,
            "latency_breach": self.latency_breach,
            "error_breach": self.error_breach,
            "fast_burn": round(self.fast_burn, 4),
            "slow_burn": round(self.slow_burn, 4),
            "fast_error_burn": round(self.fast_error_burn, 4),
            "slow_error_burn": round(self.slow_error_burn, 4),
            "p50_seconds": self.p50_seconds,
            "p99_seconds": self.p99_seconds,
            "samples": self.samples,
        }

    def render(self) -> str:
        def ms(v):
            return "n/a" if v is None else f"{v * 1e3:.2f} ms"

        state = "BREACH" if self.breach else "ok"
        return (
            f"{state:<6} p50={ms(self.p50_seconds)} "
            f"p99={ms(self.p99_seconds)} "
            f"burn fast={self.fast_burn:.1f}x slow={self.slow_burn:.1f}x "
            f"(n={self.samples})"
        )


class SloMonitor:
    """Sliding-window burn-rate evaluator for ONE tenant (module
    docstring).  Feed it cumulative latency histograms + cumulative
    error/request counts via :meth:`observe`; read :meth:`evaluate`.
    Thread-safe; snapshots older than the slow window (plus one edge
    sample) are pruned."""

    def __init__(self, tenant: str, target: SloTarget,
                 histogram_name: str = "serve.lookup_seconds"):
        self.tenant = tenant
        self.target = target
        self.histogram_name = histogram_name
        self._lock = threading.Lock()
        # (ts, cumulative latency hist, cumulative errors)
        self._snaps: Deque[Tuple[float, LogHistogram, int]] = deque()

    # -- feeding -------------------------------------------------------------

    def observe(self, hist: Optional[LogHistogram], errors: int = 0,
                now: Optional[float] = None) -> None:
        """Record one CUMULATIVE snapshot (``hist`` may be None when the
        tenant has no traffic yet — recorded as empty so windows still
        advance)."""
        ts = time.monotonic() if now is None else float(now)
        h = hist.copy() if hist is not None else LogHistogram()
        with self._lock:
            self._snaps.append((ts, h, int(errors)))
            horizon = ts - self.target.slow_window_s
            # keep ONE sample at/past the horizon: it is the far edge
            # the slow window subtracts against
            while len(self._snaps) >= 2 and self._snaps[1][0] <= horizon:
                self._snaps.popleft()

    # -- the window math -----------------------------------------------------

    def _window(self, window_s: float, now: float
                ) -> Tuple[LogHistogram, int]:
        """(latency increase, error increase) over the trailing
        ``window_s`` — newest snapshot minus the newest snapshot at or
        before the window's start (caller holds the lock)."""
        newest_ts, newest_h, newest_e = self._snaps[-1]
        edge = now - window_s
        base_h, base_e = None, 0
        for ts, h, e in self._snaps:
            if ts <= edge:
                base_h, base_e = h, e
            else:
                break
        if base_h is None:
            # whole history is inside the window: everything counts
            return newest_h.copy(), newest_e
        return newest_h.subtract(base_h), max(0, newest_e - base_e)

    def evaluate(self, now: Optional[float] = None) -> SloStatus:
        """Current :class:`SloStatus`.  With no snapshots (or an empty
        window) the burn rates are 0 — absence of traffic is not a
        breach."""
        t = self.target
        ts = time.monotonic() if now is None else float(now)
        with self._lock:
            if not self._snaps:
                fast_h, fast_e = LogHistogram(), 0
                slow_h, slow_e = LogHistogram(), 0
            else:
                fast_h, fast_e = self._window(t.fast_window_s, ts)
                slow_h, slow_e = self._window(t.slow_window_s, ts)

        def latency_burn(h: LogHistogram) -> float:
            if not h.count:
                return 0.0
            frac = h.count_above(t.p99_seconds) / h.count
            return frac / t.latency_budget

        def error_burn(errors: int, h: LogHistogram) -> float:
            requests = h.count + errors
            if not requests or t.error_rate <= 0:
                return 0.0
            return (errors / requests) / t.error_rate

        fb, sb = latency_burn(fast_h), latency_burn(slow_h)
        feb, seb = error_burn(fast_e, fast_h), error_burn(slow_e, slow_h)
        latency_breach = fb >= t.fast_burn and sb >= t.slow_burn
        error_breach = feb >= t.fast_burn and seb >= t.slow_burn
        return SloStatus(
            tenant=self.tenant,
            breach=latency_breach or error_breach,
            latency_breach=latency_breach,
            error_breach=error_breach,
            fast_burn=fb, slow_burn=sb,
            fast_error_burn=feb, slow_error_burn=seb,
            p50_seconds=fast_h.percentile(50),
            p99_seconds=fast_h.percentile(99),
            samples=fast_h.count,
            target=t,
        )


#: counters whose increase a tenant's monitor treats as request errors
#: (storage gave up / the breaker refused) when deriving the error rate
ERROR_COUNTERS = ("io.retry_exhausted", "io.remote.breaker_fast_fails")


def tenant_errors(counters: Dict[str, int]) -> int:
    """The cumulative error count a tenant's tracer counters imply."""
    return sum(int(counters.get(k, 0)) for k in ERROR_COUNTERS)
