"""Declarative streaming reader — L4 parity with the reference's
``ParquetReader`` (``ParquetReader.java``), backed by the from-scratch
columnar engine instead of parquet-mr.

Parity surface (reference line cites):
  * ``stream_content`` / ``iter_rows`` — ``streamContent`` (:47-61)
  * iterator protocol + ``estimate_size`` — Spliterator (:176-227)
  * ``read_metadata`` — (:109-117); ``metadata`` property — (:229-231)
  * ``stream_content_to_strings`` debug reader — (:86-107)
  * projection by top-level name only (:126-128); empty/None = all (:76)
  * null iff def < max-def (:146,165-167); flat-only guard (:200-202)
  * BINARY/FLBA/INT96 stringified via the type stringifier (:147-163)
  * errors wrapped as RuntimeError("Failed to read parquet") (:209-211)

The engine difference: rows here are served from decoded columnar batches
(one row group at a time), not per-cell virtual dispatch — same laziness
(a row group decodes only when iteration reaches it), TPU-shaped internals.

One front door, two engines: ``engine="host"`` decodes row groups with the
NumPy engine; ``engine="tpu"`` routes the SAME declarative API through the
fused device engine (``tpu.engine.TpuRowGroupReader`` — one packed
transfer + one compiled decode per row group, 3-stage stage‖ship‖decode
pipeline across groups), then hydrates rows from the decoded device
columns.  Cell values, null semantics, stringification, column order,
projection, and error behavior are identical between engines; DOUBLE
columns ride the bit-exact ``float64_policy='bits'`` path so TPU decode
loses nothing vs the reference's exact doubles.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Sequence, Set

import numpy as np

from ..batch.columns import ColumnBatch, RowGroupBatch
from ..format.file_read import ParquetFileReader, ReaderOptions
from ..format.metadata import ParquetMetadata
from ..format.parquet_thrift import Type
from ..format.schema import ColumnDescriptor
from .hydrate import Hydrator, supplier_of


def read_metadata(source) -> ParquetMetadata:
    """Footer-only read (``ParquetReader.readMetadata``, :109-117)."""
    with ParquetFileReader(source) as r:
        return r.metadata


def _check_dataset_schema(state: dict, schema, file_index: int) -> None:
    """Dataset contract shared by the row and batch streams: every file
    must match the first file's schema key (paths, physical and logical
    types).  ``state`` holds the key across files."""
    from ..format.schema import dataset_schema_key

    key = dataset_schema_key(schema.columns)
    if "schema_key" not in state:
        state["schema_key"] = key
    elif key != state["schema_key"]:
        raise ValueError(
            f"dataset file {file_index} disagrees with the first file's "
            "schema"
        )


def _resolve_engine(engine: str, reader: ParquetFileReader, purpose: str,
                    columns, options: Optional[ReaderOptions]) -> str:
    """Resolve host|tpu|auto for one open file, honoring the robustness
    contract: ``verify_crc`` only exists on the host decode path, so it
    PINS the engine — ``auto`` routes host (the correctness ask outranks
    the cost model) and an explicit ``tpu`` raises rather than silently
    skipping the verification it was asked for.  ``salvage`` routes
    ``auto`` to host too (salvage decode IS host decode, even on the
    device face), but an explicit ``tpu`` is honored for the BATCH face
    (the engine delegates each unit to the host salvage engine and
    ships the surviving arrays); the ROW cursor face still pins host —
    its group-row bookkeeping reads footer counts that the row-mask
    tier can shrink."""
    verify_only = options is not None and options.verify_crc \
        and not options.salvage
    salvaging = options is not None and options.salvage
    needs_host = options is not None and (options.verify_crc or options.salvage)
    if engine == "tpu" and (
        verify_only or (salvaging and purpose == "rows")
    ):
        from ..errors import UnsupportedFeatureError

        raise UnsupportedFeatureError(
            "ReaderOptions.verify_crc (and salvage, on the row cursor "
            'face) are host-engine features; use engine="host" or '
            '"auto" (which routes them to host)'
        )
    if engine == "auto":
        if needs_host:
            from ..utils import trace

            trace.decision("engine.auto", {
                "engine": "host",
                "why": "verify_crc/salvage pin the host decode path",
            })
            return "host"
        # per-FILE cost-model routing, not per-platform: the footer
        # (bytes, codecs, encodings, optionality) + a cached link probe
        # predict which engine wins this file (tpu/cost.py); decision
        # visible via trace.decisions()
        from ..tpu.cost import choose_engine

        return choose_engine(
            reader, purpose=purpose,
            columns=set(columns) if columns else None,
        ).engine
    return engine


def _unit_quarantined_rule(unit):
    """The salvage placeholder rule for one scan-delivered unit: a
    column missing from the batch is served as a placeholder/None ONLY
    when the unit's own report recorded its chunk quarantine (an
    unrecorded missing column is corrupt-footer loss and must raise).
    None in strict mode — the caller then raises on any missing column."""
    if unit.salvage is None:
        return None

    def rule(desc, u=unit):
        return u.salvage.chunk_quarantined(
            u.group_index, ".".join(desc.path)
        )

    return rule


def _was_quarantined(reader: ParquetFileReader, desc: ColumnDescriptor,
                     rg_index: int) -> bool:
    """True iff salvage actually recorded a whole-chunk quarantine for
    this (column, row group).  A column missing WITHOUT a record is a
    corrupt-but-parseable footer — substituting nulls for it would be
    silent unreported data loss, so callers must raise instead."""
    rep = reader.salvage_report
    return rep is not None and \
        rep.chunk_quarantined(rg_index, ".".join(desc.path))


def _device_batch_columns(device_cols):
    """``DeviceColumn`` → ``BatchColumn`` conversion shared by the
    sequential and scan-scheduled device batch faces (one definition of
    the ``f64_bits`` rule: DOUBLE decoded under the engine's 'bits'
    policy rides as exact int64 bit patterns).  Salvage placeholders
    (already ``BatchColumn(quarantined=True)``) pass through unchanged —
    they stay IN POSITION, exactly like the host batch face."""
    from ..batch.columns import BatchColumn
    from ..format.parquet_thrift import Type as _T
    from ..query.expr import ComputedColumn

    def conv(dc):
        if isinstance(dc, BatchColumn):
            return dc
        if isinstance(dc, ComputedColumn):
            # computed outputs are exact by construction (lossy-DOUBLE
            # inputs reject at plan time) — never bit-form
            return BatchColumn(dc.descriptor, dc.values, dc.mask)
        return BatchColumn(
            dc.descriptor, dc.values, dc.mask, dc.lengths,
            dc.def_levels, dc.rep_levels,
            f64_bits=dc.descriptor.physical_type == _T.DOUBLE,
        )

    return [conv(dc) for dc in device_cols]


def _host_batch_columns(selected, batch, gi: int, quarantined=None):
    """Ordered ``BatchColumn`` list for one host-decoded row group — THE
    definition of the batch face's positional contract, shared by the
    sequential and scan-scheduled streams (so they cannot drift).

    ``quarantined(desc) -> bool`` supplies the salvage placeholder rule
    (sequential path only; the scan path rejects salvage and passes
    None): a recorded quarantine keeps column ORDER intact via a
    ``values=None`` placeholder that fails loudly on data access, while
    an unrecorded missing column is corrupt-footer loss and raises."""
    from ..batch.columns import BatchColumn

    by_path = {b.descriptor.path: b for b in batch.columns}
    cols = []
    for desc in selected:
        cb = by_path.get(desc.path)
        if cb is None:
            if quarantined is not None and quarantined(desc):
                cols.append(BatchColumn(desc, None, quarantined=True))
                continue
            raise ValueError(f"row group {gi} missing column {desc.path}")
        if cb.rep_levels is not None:
            cols.append(BatchColumn(
                desc, cb.values,
                lengths=(
                    cb.values.lengths()
                    if hasattr(cb.values, "lengths")
                    else None
                ),
                def_levels=cb.def_levels,
                rep_levels=cb.rep_levels,
            ))
            continue
        dense, mask = cb.dense()
        lens = dense.lengths() if hasattr(dense, "lengths") else None
        cols.append(BatchColumn(desc, dense, mask, lens))
    return cols


def _host_expr_columns(exprs, batch):
    """Host-leg expression outputs for one decoded row group: the device
    leg's bit-equal twin (docs/query.md).  Evaluates over the same
    canonical null-zeroed lanes the fused executable sees, so the two
    legs cannot drift."""
    from ..batch.columns import BatchColumn
    from ..query.expr import computed_descriptor, eval_expr_host
    from ..scan.executor import _batch_resolver

    resolve = _batch_resolver(batch)
    n = batch.num_rows
    cols = []
    for en, et in exprs:
        vals, mask = eval_expr_host(et, resolve, n)
        cols.append(
            BatchColumn(computed_descriptor(en, vals.dtype), vals, mask)
        )
    return cols


def _ordered_cursors(selected, batch, quarantined=None):
    """Ordered cell cursors for one host-decoded row group — the ROW
    face's positional contract, shared by the sequential and
    scan-scheduled streams (the batch-face twin is
    :func:`_host_batch_columns`).

    ``quarantined(desc) -> bool`` supplies the salvage placeholder rule
    (sequential path only): a recorded quarantine serves ``_NullCursor``
    cells; an unrecorded missing column raises.  The flat-only guard is
    reference parity (IllegalStateException "Unexpected repetition",
    ``ParquetReader.java:200-202``)."""
    by_name = {b.descriptor.path: b for b in batch.columns}
    ordered = []
    for desc in selected:
        b = by_name.get(desc.path)
        if b is None:
            if quarantined is not None and quarantined(desc):
                ordered.append(_NullCursor(desc))
                continue
            raise ValueError(f"row group missing column {desc.path}")
        if b.rep_levels is not None and np.any(b.rep_levels != 0):
            raise RuntimeError(
                "Failed to read parquet",
                ValueError("Unexpected repetition"),
            )
        ordered.append(_ColumnCursor(b))
    return ordered


class _ColumnCursor:
    """Per-column cursor over a decoded batch, serving API-typed cells."""

    __slots__ = ("batch", "desc", "_stringify")

    def __init__(self, batch: ColumnBatch):
        self.batch = batch
        self.desc = batch.descriptor
        pt = self.desc.physical_type
        self._stringify = pt in (Type.BYTE_ARRAY, Type.FIXED_LEN_BYTE_ARRAY, Type.INT96)

    def cell(self, i: int):
        v = self.batch.cell(i)
        if v is None:
            return None
        if self._stringify:
            # Parity: BINARY/FLBA/INT96 stringified (ParquetReader.java:147-163)
            if isinstance(v, np.ndarray):
                v = v.tobytes()
            return self.desc.primitive.stringify(v)
        if isinstance(v, np.bool_):
            return bool(v)
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        return v


class _NullCursor:
    """Cursor for a salvage-quarantined column: every cell is None.

    Served only under ``ReaderOptions(salvage=True)`` when the file
    reader had to drop a column chunk — the row stream keeps flowing,
    the loss is explicit in ``salvage_report`` (not silent: strict mode
    raises on the same file)."""

    __slots__ = ("desc",)

    def __init__(self, desc: ColumnDescriptor):
        self.desc = desc

    def cell(self, i: int):
        return None


_CELL_BLOCK = 1 << 16


class _BlockCursor:
    """Cursor converting API-typed cells lazily in blocks (the device
    path): the fetched NumPy arrays stay resident, and Python cell
    objects materialize ``_CELL_BLOCK`` at a time — the forward-moving
    row loop keeps O(block) boxed objects live instead of O(group-rows)
    (a 1M-row × 16-col group would otherwise hold ~16M objects at
    once).  Conversion stays vectorized per block, so the cost per cell
    is unchanged."""

    __slots__ = ("desc", "_convert", "_lo", "_cells")

    def __init__(self, desc: ColumnDescriptor, convert):
        self.desc = desc
        self._convert = convert  # (lo, hi) -> list of API cells
        self._lo = -1
        self._cells: list = []

    def cell(self, i: int):
        lo = (i // _CELL_BLOCK) * _CELL_BLOCK
        if lo != self._lo:
            self._cells = self._convert(lo, lo + _CELL_BLOCK)
            self._lo = lo
        return self._cells[i - lo]


def _device_column_cells(desc, vals, mask, lens) -> list:
    """Convert one decoded device column (already fetched to host NumPy)
    into the exact cell values the host cursor serves: Python scalars,
    stringified BINARY/FLBA/INT96, None at nulls.  DOUBLE decoded under
    ``float64_policy='bits'`` (int64 bit patterns) is bit-cast back —
    bit-exact parity with the host engine."""
    if lens is not None:  # BYTE_ARRAY: padded rows + lengths
        ml = vals.shape[1] if vals.ndim == 2 else 0
        buf = vals.tobytes()
        stringify = desc.primitive.stringify
        cells = [
            stringify(buf[i * ml : i * ml + ln])
            for i, ln in enumerate(lens.tolist())
        ]
    elif vals.ndim == 2:  # FLBA / INT96 raw byte rows
        w = vals.shape[1]
        buf = vals.tobytes()
        stringify = desc.primitive.stringify
        cells = [
            stringify(buf[i * w : (i + 1) * w]) for i in range(vals.shape[0])
        ]
    else:
        if desc.physical_type == Type.DOUBLE and vals.dtype == np.int64:
            vals = vals.view(np.float64)  # 'bits' policy round-trip
        cells = vals.tolist()
    if mask is not None:
        for i in np.flatnonzero(mask).tolist():
            cells[i] = None
    return cells


_PACK_CACHE: dict = {}


def _fetch_packed(leaves: list) -> list:
    """One device→host transfer for a heterogeneous list of jax arrays:
    a tiny jitted program bitcasts everything to uint8 and concatenates,
    so the host pays ONE transfer's fixed cost instead of one per array
    (per-transfer overhead dominates on tunnelled links).  Shapes are
    HWM-bucketed by the engine, so the pack program caches well."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    sig = tuple((tuple(a.shape), str(a.dtype)) for a in leaves)
    fn = _PACK_CACHE.get(sig)
    if fn is None:
        def pack(*xs):
            parts = []
            for x in xs:
                if x.dtype == jnp.bool_:
                    x = x.astype(jnp.uint8)
                if x.dtype != jnp.uint8:
                    x = lax.bitcast_convert_type(x, jnp.uint8)
                parts.append(x.reshape(-1))
            return jnp.concatenate(parts)
        fn = jax.jit(pack)
        if len(_PACK_CACHE) > 256:
            _PACK_CACHE.clear()
        _PACK_CACHE[sig] = fn
    buf = np.asarray(fn(*leaves))
    out, off = [], 0
    for a in leaves:
        dt = np.dtype(str(a.dtype))
        nb = int(np.prod(a.shape)) * dt.itemsize
        seg = buf[off : off + nb]
        arr = (
            seg.view(np.bool_) if dt == np.bool_ else seg.view(dt)
        ).reshape(a.shape)
        out.append(arr)
        off += nb
    return out


class ParquetReader:
    """Streaming row reader; itself an iterator and a context manager.

    ``engine`` selects the decode engine behind the same API surface:
    ``"host"`` (NumPy, the default), ``"tpu"`` (the fused device engine),
    or ``"auto"`` — on a TPU backend, a per-file footer cost model
    (``tpu.cost``) routes each file to whichever engine the model says
    wins (memcpy-class files stay host; per-value-decode files go
    device); on any other backend, host.

    ``options`` (a :class:`~parquet_floor_tpu.ReaderOptions`) carries the
    robustness knobs of the underlying file reader.  The one most callers
    want: ``ReaderOptions(verify_crc=True)`` CRC32-checks every page
    payload against the writer's stamp before decode — off by default
    (parity with parquet-mr), but the only *guaranteed* detection of a
    bit flip inside a page payload (an UNCOMPRESSED page otherwise
    decodes silently wrong; a compressed one usually — not always — trips
    the codec).  ``io_retries`` adds bounded retry-with-backoff for
    transient ``OSError`` reads.  ``verify_crc``/``salvage`` are
    host-engine features and PIN the engine: ``"auto"`` routes such
    reads to host, and an explicit ``engine="tpu"`` raises rather than
    silently skipping the verification it was asked for.  See
    ``docs/robustness.md``.
    """

    def __init__(self, source, hydrator_supplier, columns: Optional[Sequence[str]] = None,
                 engine: str = "host", predicate=None,
                 options: Optional[ReaderOptions] = None):
        if engine not in ("host", "tpu", "auto"):
            raise ValueError(f"bad engine {engine!r}: expected host|tpu|auto")
        self._reader = ParquetFileReader(source, options=options)
        try:
            engine = _resolve_engine(
                engine, self._reader, "rows", columns, options
            )
        except BaseException:
            self._reader.close()
            raise
        self.engine = engine
        schema = self._reader.schema
        want = set(columns) if columns else None
        selected: List[ColumnDescriptor] = [
            c for c in schema.columns
            if want is None or c.path[0] in want
        ]
        self.columns = selected
        self._filter: Optional[Set[str]] = (
            {c.path[0] for c in selected} if columns else None
        )
        self.hydrator: Hydrator = supplier_of(hydrator_supplier).get(selected)
        # predicate pushdown (native win, no reference counterpart): row
        # groups whose statistics/Bloom filters prove no row can match
        # are skipped before any page is read, on either engine
        try:
            self._keep: Optional[Set[int]] = (
                set(predicate.row_groups(self._reader))
                if predicate is not None
                else None
            )
        except BaseException:
            self._reader.close()  # don't leak the open file
            raise
        self._rg_index = 0
        self._row = 0
        self._cursors: Optional[List[_ColumnCursor]] = None
        self._rg_rows = 0
        self._finished = False
        self._tpu = None
        self._tpu_gen = None
        self._tpu_pending: list = []
        self._conv_fut = None
        self._conv_pool = None
        if engine == "tpu" and selected:
            from ..tpu.engine import TpuRowGroupReader

            try:
                # 'bits' decodes DOUBLE as exact int64 bit patterns on any
                # backend; _device_column_cells casts back to float64 on
                # host.  Index-form dictionaries: fetch the packed index
                # stream + one small pool (cached) instead of gathered
                # values — and convert once per distinct value, not per
                # cell.
                self._tpu = TpuRowGroupReader(
                    self._reader, float64_policy="bits", dict_form="index"
                )
                self._pool_cells: dict = {}
            except BaseException as e:
                self._reader.close()  # engine never took ownership
                if isinstance(e, RuntimeError) and "64-bit" in str(e):
                    raise RuntimeError(
                        'ParquetReader(engine="tpu") needs 64-bit JAX '
                        "types: call "
                        'jax.config.update("jax_enable_x64", True) first '
                        "(not flipped automatically — it changes dtype "
                        "promotion for all JAX code in the process)"
                    ) from None
                raise

    # -- metadata ----------------------------------------------------------

    @property
    def metadata(self) -> ParquetMetadata:
        """Open-reader footer access (``metaData()``, :229-231)."""
        return self._reader.metadata

    @property
    def salvage_report(self):
        """The underlying reader's :class:`SalvageReport` (None unless
        ``ReaderOptions(salvage=True)``).  The report object outlives
        ``close()``, so losses stay accountable after the stream ends."""
        return self._reader.salvage_report

    def estimate_size(self) -> int:
        """Exact total row count from the footer (:219-222); with a
        predicate, the rows of the surviving row groups."""
        if self._keep is None:
            return self._reader.record_count
        return sum(
            int(rg.num_rows or 0)
            for i, rg in enumerate(self._reader.row_groups)
            if i in self._keep
        )

    def try_split(self):
        """Always None — the reference's spliterator declines to split
        (``trySplit``, :214-217).  Parallel reading lives in
        ``parallel.shard``/``parallel.multihost`` instead."""
        return None

    def characteristics(self) -> frozenset:
        """The reference's spliterator characteristics
        (ORDERED | NONNULL | DISTINCT, :224-227), as flag names."""
        return frozenset({"ORDERED", "NONNULL", "DISTINCT"})

    # -- iteration ---------------------------------------------------------

    def _dict_form_cells(self, dc, idx_np, mask_np) -> list:
        """Cells for an index-form dictionary column: one conversion per
        distinct pool value (cached per pool), then a list gather by the
        packed index stream."""
        import jax

        kind, ckey, *arrs = dc.dict_ref
        # strings cache by the engine's CONTENT key (stable across the
        # file); never by id() — ids are recycled after GC, which would
        # alias a freed pool with a new one (wrong cells, not just a
        # crash).  The key also carries the column's stringify semantics:
        # two columns can share byte-identical pools but different
        # logical types (str vs hex rendering).  Numeric pools are
        # per-group and tiny: convert fresh.
        desc = dc.descriptor
        # LogicalAnnotation is hashable and captures kind AND params
        # (e.g. DECIMAL scale — two columns can share a byte-identical
        # pool yet render at different scales)
        lt = desc.primitive.logical_type
        key = (
            (ckey, desc.physical_type, lt) if ckey is not None else None
        )
        pool = self._pool_cells.get(key) if key is not None else None
        if pool is None:
            if kind in ("dev", "host_str"):  # string pool
                rows, lens = (
                    jax.device_get(tuple(arrs))
                    if kind == "dev"
                    else (np.asarray(arrs[0]), np.asarray(arrs[1]))
                )
                ml = rows.shape[1] if rows.ndim == 2 else 0
                buf = rows.tobytes()
                stringify = dc.descriptor.primitive.stringify
                pool = [
                    stringify(buf[i * ml : i * ml + ln])
                    for i, ln in enumerate(lens.tolist())
                ]
            else:  # typed numeric pool, already host-side
                vals = arrs[0]
                if (
                    dc.descriptor.physical_type == Type.DOUBLE
                    and vals.dtype == np.int64
                ):
                    vals = vals.view(np.float64)  # 'bits' round-trip
                pool = vals.tolist()
            if key is not None:
                self._pool_cells[key] = pool
        cells = [pool[i] for i in idx_np.tolist()]
        if mask_np is not None:
            for i in np.flatnonzero(mask_np).tolist():
                cells[i] = None
        return cells

    def _convert_group_tpu(self, group) -> list:
        """Fused-decoded device group → per-column API cell cursors (same
        cells, same order, same errors as the host cursor path)."""
        import jax

        ordered = []
        for desc in self.columns:
            dc = group.get(".".join(desc.path))
            if dc is None:
                raise ValueError(f"row group missing column {desc.path}")
            if dc.rep_levels is not None:
                # Flat-only guard, parity with the host engine (and the
                # reference's IllegalStateException "Unexpected
                # repetition", ParquetReader.java:200-202).
                if np.any(np.asarray(dc.rep_levels) != 0):
                    raise RuntimeError(
                        "Failed to read parquet",
                        ValueError("Unexpected repetition"),
                    )
                raise ValueError(
                    "cell() requires a flat (non-repeated) column"
                )
            ordered.append(dc)
        # ONE device→host transfer for the whole group (see
        # _fetch_packed: per-transfer overhead dominates on tunnelled
        # links, so the group's arrays are packed on device first);
        # Python cell conversion is then lazy per block (_BlockCursor)
        tree = [(dc.values, dc.mask, dc.lengths) for dc in ordered]
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        host = jax.tree_util.tree_unflatten(
            treedef, _fetch_packed(leaves) if leaves else []
        )
        cursors = []
        for dc, (v, m, ln) in zip(ordered, host):
            if dc.dict_ref is not None:
                def conv(lo, hi, dc=dc, v=v, m=m):
                    return self._dict_form_cells(
                        dc, v[lo:hi], None if m is None else m[lo:hi]
                    )
            else:
                def conv(lo, hi, dc=dc, v=v, m=m, ln=ln):
                    return _device_column_cells(
                        dc.descriptor, v[lo:hi],
                        None if m is None else m[lo:hi],
                        None if ln is None else ln[lo:hi],
                    )
            cursors.append(_BlockCursor(dc.descriptor, conv))
        return cursors

    def _pull_convert_tpu(self) -> list:
        """next(engine generator) + cell conversion (runs on the main
        thread or the one-deep prefetch worker, never both at once)."""
        try:
            group = next(self._tpu_gen)
        except StopIteration:  # pragma: no cover - indices cover the tail
            raise RuntimeError(
                "device engine ended before the last row group"
            ) from None
        return self._convert_group_tpu(group)

    def _advance_row_group_tpu(self) -> bool:
        n_groups = len(self._reader.row_groups)
        while True:
            if self._tpu_gen is None:
                # ONE ordered kept-index list drives the generator, the
                # pairing of decoded groups with footer rows, and the
                # prefetch decision — _rg_index keeps the host path's
                # meaning (the group just consumed is _rg_index - 1), so
                # state()/restore() agree across engines and predicates
                pending = [
                    i for i in range(self._rg_index, n_groups)
                    if self._keep is None or i in self._keep
                ]
                if not pending:
                    self._finished = True
                    return False
                names = [c.path[0] for c in self.columns]
                self._tpu_pending = pending
                self._tpu_gen = self._tpu.iter_row_groups(
                    columns=names, indices=list(pending)
                )
            if not self._tpu_pending:
                self._finished = True
                return False
            if self._conv_fut is not None:
                try:
                    cursors = self._conv_fut.result()
                finally:
                    # clear even when result() raises: the error is being
                    # DELIVERED here, and close() must not re-report it
                    # as a discarded prefetch error
                    self._conv_fut = None
            else:
                cursors = self._pull_convert_tpu()
            idx = self._tpu_pending.pop(0)
            rg_rows = int(self._reader.row_groups[idx].num_rows or 0)
            self._rg_index = idx + 1
            if self._tpu_pending:
                # convert the NEXT group in the background while the
                # caller hydrates this one: the device→host transfer
                # releases the GIL, so the fetch cost hides under the
                # Python row loop
                if self._conv_pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._conv_pool = ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="pftpu-rowconv"
                    )
                self._conv_fut = self._conv_pool.submit(self._pull_convert_tpu)
            self._cursors = cursors
            self._rg_rows = rg_rows
            self._row = 0
            if self._rg_rows > 0:
                return True

    def _advance_row_group(self) -> bool:
        if self._tpu is not None:
            return self._advance_row_group_tpu()
        while self._rg_index < len(self._reader.row_groups):
            if self._keep is not None and self._rg_index not in self._keep:
                self._rg_index += 1  # predicate-pruned group
                continue
            gi = self._rg_index
            batch = self._reader.read_row_group(gi, self._filter)
            self._rg_index += 1
            self._cursors = _ordered_cursors(
                self.columns, batch,
                quarantined=lambda d: _was_quarantined(self._reader, d, gi),
            )
            self._rg_rows = batch.num_rows
            self._row = 0
            if self._rg_rows > 0:
                return True
        self._finished = True
        return False

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self):
        try:
            if self._finished:
                raise StopIteration
            if self._cursors is None or self._row >= self._rg_rows:
                if not self._advance_row_group():
                    raise StopIteration
            h = self.hydrator
            record = h.start()
            i = self._row
            for cursor in self._cursors:
                record = h.add(record, cursor.desc.path[0], cursor.cell(i))
            self._row += 1
            return h.finish(record)
        except StopIteration:
            raise
        except Exception as e:  # floorlint: disable=FL-EXC001
            # Parity: the reference wraps EVERY iteration failure —
            # including IO — as RuntimeError (ParquetReader.java:209-211),
            # and test_api_parity pins that; the cause chain keeps the
            # real class reachable.
            raise RuntimeError("Failed to read parquet") from e

    def _drain_prefetch(self) -> Optional[Exception]:
        """Retire the one-deep prefetch future, returning (not raising)
        its error: discarded lookahead must never abort a close/restore."""
        err = None
        if self._conv_fut is not None:
            try:
                self._conv_fut.result()
            except Exception as e:
                err = e
            self._conv_fut = None
        return err

    def close(self) -> None:
        err = self._drain_prefetch()
        if self._conv_pool is not None:
            self._conv_pool.shutdown(wait=False)
            self._conv_pool = None
        if self._tpu_gen is not None:
            self._tpu_gen.close()
            self._tpu_gen = None
        if self._tpu is not None:
            self._tpu.close()  # owns (and closes) the shared file reader
        else:
            self._reader.close()
        if err is not None:
            # a background conversion failed and no read surfaced it —
            # don't let it vanish.  Warn AFTER every resource is released
            # (warnings-as-errors must not leak the pool/engine/file).
            import warnings

            warnings.warn(
                "ParquetReader.close() discarded a background prefetch "
                f"error: {err!r}",
                RuntimeWarning,
                stacklevel=2,
            )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- checkpoint / resume (SURVEY.md §5: the resumable row-group cursor
    # the reference's streaming structure implies but never exposes) -------

    def state(self) -> dict:
        """Serializable scan position: resume a later reader here with
        :meth:`restore`.  Valid between rows; cheap (two ints)."""
        if self._cursors is None or self._row >= self._rg_rows:
            # next row comes from the next group boundary
            return {"row_group": self._rg_index, "row_in_group": 0}
        return {"row_group": self._rg_index - 1, "row_in_group": self._row}

    def restore(self, state: dict) -> "ParquetReader":
        """Position this reader at a previously saved :meth:`state`.

        The target row group is re-decoded (row groups are the atomic
        decode unit); rows before ``row_in_group`` are skipped O(1).
        """
        rg = int(state["row_group"])
        row = int(state["row_in_group"])
        n_groups = len(self._reader.row_groups)
        if rg < 0 or rg > n_groups:
            raise ValueError(f"row_group {rg} outside file with {n_groups}")
        if row < 0 or (rg == n_groups and row):
            raise ValueError(f"bad row_in_group {row} for row_group {rg}")
        self._rg_index = rg
        self._cursors = None
        self._rg_rows = 0
        self._finished = False
        self._row = 0
        if self._tpu_gen is not None:
            # device pipeline is positional: restart it at the new group
            self._drain_prefetch()
            self._tpu_gen.close()
            self._tpu_gen = None
        if rg < n_groups and row:
            if not self._advance_row_group():
                raise ValueError("saved state points past end of file")
            if row > self._rg_rows:
                raise ValueError(
                    f"row_in_group {row} exceeds group of {self._rg_rows}"
                )
            self._row = row
        return self

    # -- batch access (native win; no reference counterpart) ---------------

    def read_row_group_batch(self, index: int) -> RowGroupBatch:
        return self._reader.read_row_group(index, self._filter)

    @staticmethod
    def stream_batches(source, batch_hydrator=None,
                       columns: Optional[Sequence[str]] = None,
                       engine: str = "host", predicate=None,
                       options: Optional[ReaderOptions] = None,
                       scan_options=None):
        """The BATCH face of the Hydrator boundary: one plugin call per
        ROW GROUP, columns as arrays in column order (the
        ``HydratorSupplier.java:10-15`` ordering contract lifted to
        batch granularity — SURVEY.md §7 L3's "zero-copy batch/Arrow-
        style access").

        ``batch_hydrator`` is a ``BatchHydrator`` / supplier / callable
        (``columns -> BatchHydrator``); ``None`` yields the raw
        ``BatchColumn`` lists.  ``engine`` as in ``stream_content``:
        "host" serves NumPy arrays, "tpu" serves device-resident
        ``jax.Array``s from the fused engine (no device→host copy
        unless the plugin takes one — export via DLPack /
        ``BatchColumn.to_arrow()`` / ``batch_to_arrow``), "auto" routes
        by the footer cost model.  ``predicate`` skips row groups whose
        statistics prove no match; the yielded ``group_index`` values
        stay the file's real group indices.

        ``source`` may be a LIST/TUPLE of sources (a dataset, as in
        ``stream_content``): batches stream file after file in order,
        one open file at a time, every file schema-checked against the
        first; the supplier is called ONCE (first file's columns) and
        ``group_index`` stays each file's real group index.  With
        ``engine="auto"`` each file routes independently.

        Returns a generator.  The file opens on FIRST iteration (so a
        generator closed before any ``next()`` never opens it) and
        closes when the generator is exhausted or closed.

        With ``options=ReaderOptions(salvage=True)`` a chunk the reader
        had to quarantine arrives as a PLACEHOLDER ``BatchColumn`` with
        ``quarantined=True`` and ``values=None`` — column order (the
        positional contract above) is preserved, and consumers that
        touch the placeholder's data fail loudly instead of silently
        reading a shifted column.  The quarantine is recorded in the
        reader's ``SalvageReport``; the plain generator exposes no
        report accessor — when you need the report, use
        ``ParquetReader.spliterator(...)`` (its ``salvage_report``
        property survives close) or drive ``ParquetFileReader``
        directly.

        ``scan_options`` (a :class:`~parquet_floor_tpu.scan.ScanOptions`)
        routes the stream through the scan scheduler (``docs/scan.md``):
        coalesced vectored reads and bounded cross-file prefetch, with
        work running ahead of the consumer.  ``engine="host"`` (and
        ``"auto"``, which the scheduler pins to host) decodes through
        ``scan.DatasetScanner``; ``engine="tpu"`` through
        ``scan.scan_device_groups`` — where the engine's
        stage‖ship‖decode pipeline crosses file boundaries instead of
        draining at each file's end.  Salvage is rejected under scan
        (same ``UnsupportedFeatureError`` contract as the TPU engine).

        With ``scan_options=ScanOptions(pushdown=True)`` and a
        ``predicate`` on ``engine="tpu"``, the predicate additionally
        evaluates INSIDE each group's fused decode executable and the
        yielded device batches carry only the surviving rows —
        device-compacted, so D2H (when the plugin takes one) ships
        results, not columns (``docs/pushdown.md``).  Batch row counts
        then vary per group.  ``ScanOptions.aggregate`` does not stream
        batches at all — use ``scan.scan_aggregate`` for aggregate
        queries.

        For TRAINING consumption — seeded shuffling, exact-size epoch
        batches, host sharding, and mid-epoch checkpoint/resume — use
        ``parquet_floor_tpu.data.DataLoader`` (``docs/data.md``) instead
        of re-batching this stream by hand.
        """
        if engine not in ("host", "tpu", "auto"):
            raise ValueError(f"bad engine {engine!r}: expected host|tpu|auto")
        if scan_options is not None:
            if getattr(scan_options, "aggregate", None) is not None:
                raise ValueError(
                    "ScanOptions.aggregate yields partial states, not "
                    "batches — use scan.scan_aggregate for aggregate "
                    "queries"
                )
            if getattr(scan_options, "pushdown", False) and \
                    predicate is not None and engine != "tpu":
                from ..errors import UnsupportedFeatureError

                raise UnsupportedFeatureError(
                    "ScanOptions.pushdown is the DEVICE scan leg's "
                    "feature (docs/pushdown.md): pass engine='tpu', or "
                    "drop pushdown= for a host scan"
                )
            sources = (
                list(source) if isinstance(source, (list, tuple)) else [source]
            )
            if not sources:
                raise ValueError("dataset stream needs at least one source")
            return ParquetReader._stream_batches_scan(
                sources, batch_hydrator, columns, engine, predicate,
                options, scan_options,
            )
        if isinstance(source, (list, tuple)):
            if not source:
                raise ValueError("dataset stream needs at least one source")

            def dgen():
                state: dict = {}
                for i, src in enumerate(source):
                    yield from ParquetReader._stream_batches_one(
                        src, batch_hydrator, columns, engine, predicate,
                        state, i, options,
                    )

            return dgen()
        return ParquetReader._stream_batches_one(
            source, batch_hydrator, columns, engine, predicate, {}, 0, options
        )

    @staticmethod
    def _stream_batches_one(source, batch_hydrator, columns, engine,
                            predicate, state: dict, file_index: int,
                            options: Optional[ReaderOptions] = None):
        """One file's batch stream; ``state`` carries the dataset-wide
        hydrator and schema key across files."""
        from .hydrate import batch_supplier_of

        def gen():
            reader = ParquetFileReader(source, options=options)
            closer = reader  # replaced by the engine once it takes ownership
            try:
                eng = _resolve_engine(engine, reader, "batch", columns, options)
                schema = reader.schema
                _check_dataset_schema(state, schema, file_index)
                want = set(columns) if columns else None
                selected = [
                    c for c in schema.columns
                    if want is None or c.path[0] in want
                ]
                flt = {c.path[0] for c in selected} if columns else None
                hyd = state.get("hyd")
                if hyd is None:
                    hyd = state["hyd"] = (
                        batch_supplier_of(batch_hydrator).get(selected)
                    )
                keep = (
                    set(predicate.row_groups(reader))
                    if predicate is not None
                    else None
                )
                if eng == "tpu":
                    from ..tpu.engine import TpuRowGroupReader

                    tpu = TpuRowGroupReader(
                        reader, float64_policy="bits", dict_form="gather"
                    )
                    closer = tpu  # owns (and closes) the file reader
                    names = [c.path[0] for c in selected]
                    indices = [
                        i for i in range(len(reader.row_groups))
                        if keep is None or i in keep
                    ]
                    groups = tpu.iter_row_groups(
                        columns=names, indices=indices
                    )
                    from ..batch.columns import BatchColumn

                    def pick(group, desc, gi):
                        dc = group.get(".".join(desc.path))
                        if dc is not None:
                            return dc
                        if _was_quarantined(reader, desc, gi):
                            # salvage (device face): the chunk stays IN
                            # POSITION as a fail-loudly placeholder
                            return BatchColumn(desc, None, quarantined=True)
                        raise ValueError(
                            f"row group {gi} missing column {desc.path}"
                        )

                    for gi, group in zip(indices, groups):
                        cols = _device_batch_columns(
                            pick(group, desc, gi) for desc in selected
                        )
                        yield hyd.batch(gi, cols)
                    return
                for gi in range(len(reader.row_groups)):
                    if keep is not None and gi not in keep:
                        continue
                    batch = reader.read_row_group(gi, flt)
                    cols = _host_batch_columns(
                        selected, batch, gi,
                        quarantined=lambda d, gi=gi: _was_quarantined(
                            reader, d, gi
                        ),
                    )
                    yield hyd.batch(gi, cols)
            finally:
                closer.close()

        return gen()

    @staticmethod
    def _stream_batches_scan(sources, batch_hydrator, columns, engine,
                             predicate, options, scan_options):
        """Scan-scheduled dataset batches (docs/scan.md): host decode
        through ``scan.DatasetScanner``, device decode through
        ``scan.scan_device_groups`` — either way, reads and decode run
        across files ahead of the consumer, bounded by the scan byte
        budget.  The supplier is called once, with the first file's
        selected columns, and ``group_index`` stays each file's real
        group index (the sequential dataset contract)."""
        from .hydrate import batch_supplier_of

        exprs = tuple(getattr(scan_options, "project_exprs", ()) or ())
        if exprs and options is not None and getattr(options, "salvage", False):
            from ..errors import UnsupportedFeatureError

            raise UnsupportedFeatureError(
                "ScanOptions.project_exprs does not compose with salvage: "
                "a quarantined input column has no values to evaluate "
                "over — scan without salvage=True, or drop project_exprs"
            )

        def host_gen():
            from ..scan import DatasetScanner

            scan_cols = columns
            if exprs and columns is not None:
                # widen the scan to cover expression inputs; the caller's
                # projection is restored at delivery below
                from ..query.expr import expr_columns

                need = set(columns)
                for _en, et in exprs:
                    need |= {c.split(".")[0] for c in expr_columns(et)}
                scan_cols = sorted(need)
            scanner = DatasetScanner(
                sources, columns=scan_cols, options=options,
                scan=scan_options, predicate=predicate,
            )
            try:
                hyd = None
                want = set(columns) if columns is not None else None
                deliver = None
                for unit in scanner:
                    if deliver is None:
                        deliver = [
                            c for c in scanner.columns
                            if want is None or c.path[0] in want
                        ]
                    cols = _host_batch_columns(
                        deliver, unit.batch, unit.group_index,
                        quarantined=_unit_quarantined_rule(unit),
                    )
                    if exprs:
                        cols = cols + _host_expr_columns(exprs, unit.batch)
                    if hyd is None:
                        hyd = batch_supplier_of(batch_hydrator).get(
                            [bc.descriptor for bc in cols]
                        )
                    yield hyd.batch(unit.group_index, cols)
            finally:
                scanner.close()

        if engine == "tpu":
            def dgen():
                from ..errors import UnsupportedFeatureError
                from ..scan import scan_device_groups

                hyd = None
                it = scan_device_groups(
                    sources, columns=columns, options=options,
                    scan=scan_options, predicate=predicate,
                )
                try:
                    while True:
                        try:
                            _fi, gi, group = next(it)
                        except StopIteration:
                            return
                        except UnsupportedFeatureError as e:
                            if hyd is not None:
                                # mid-stream: batches already escaped —
                                # a silent restart would replay rows
                                raise
                            from ..utils import trace

                            trace.decision("engine.pushdown", {
                                "action": "host_fallback",
                                "why": str(e)[:200],
                            })
                            yield from host_gen()
                            return
                        if hyd is None:
                            # schema-ordered by scan_device_groups (with
                            # computed outputs after the schema columns) —
                            # the same positional contract as the
                            # sequential face
                            hyd = batch_supplier_of(batch_hydrator).get(
                                [dc.descriptor for dc in group.values()]
                            )
                        yield hyd.batch(
                            gi, _device_batch_columns(group.values())
                        )
                finally:
                    it.close()

            return dgen()

        def gen():
            if engine == "auto":
                from ..utils import trace

                trace.decision("engine.auto", {
                    "engine": "host",
                    "why": "the scan scheduler decodes dataset batches "
                           "on host; pass engine='tpu' for device scan",
                })
            yield from host_gen()

        return gen()

    # -- static factories (reference API verbs) ----------------------------

    @staticmethod
    def stream_content(source, hydrator_supplier, columns: Optional[Sequence[str]] = None,
                       engine: str = "host", predicate=None,
                       options: Optional[ReaderOptions] = None,
                       scan_options=None):
        """Stream hydrated records (``streamContent``, :47-61).

        Returns an iterator that owns the file and closes it on exhaustion
        or ``.close()`` (stream-close parity, :80-84).  ``engine="tpu"``
        hydrates the same rows from fused device-decoded column batches;
        ``predicate`` (see ``parquet_floor_tpu.col``) skips row groups
        whose statistics/Bloom filters prove no row can match.  This is
        GROUP-level pushdown, not row filtering: a surviving group
        streams in full, including its rows that do not match.

        ``source`` may be a LIST/TUPLE of sources (a dataset): rows
        stream file after file in order, with one file open at a time;
        every file must carry the same schema as the first.

        ``scan_options`` (a :class:`~parquet_floor_tpu.scan.ScanOptions`)
        streams the same rows through the scan scheduler instead
        (``docs/scan.md``): coalesced vectored reads, and row groups
        decoded across files ahead of the consumer under a byte budget.
        Rows under scan decode on the host engine — ``engine="tpu"``
        raises (use ``stream_batches(engine="tpu", scan_options=...)``
        for device scan).  ``ReaderOptions(salvage=True)`` is honored:
        quarantined columns serve ``None`` cells and the iterator's
        ``salvage_report`` exposes the dataset-level fold.
        """
        if scan_options is not None:
            if engine == "tpu":
                raise ValueError(
                    "scan-scheduled row streams decode on the host "
                    'engine; use engine="host"/"auto", or '
                    'stream_batches(engine="tpu", scan_options=...) for '
                    "device scan"
                )
            sources = (
                list(source) if isinstance(source, (list, tuple)) else [source]
            )
            if not sources:
                raise ValueError("dataset stream needs at least one source")
            return _ScanRowIterator(
                sources, hydrator_supplier, columns, predicate, options,
                scan_options,
            )
        if isinstance(source, (list, tuple)):
            return _DatasetIterator(
                list(source), hydrator_supplier, columns, engine, predicate,
                options,
            )
        reader = ParquetReader(source, hydrator_supplier, columns,
                               engine=engine, predicate=predicate,
                               options=options)
        return _ClosingIterator(reader)

    @staticmethod
    def spliterator(source, hydrator_supplier, columns: Optional[Sequence[str]] = None,
                    engine: str = "host", predicate=None,
                    options: Optional[ReaderOptions] = None) -> "ParquetReader":
        """The raw cursor object (``spliterator``, :63-78)."""
        return ParquetReader(source, hydrator_supplier, columns,
                             engine=engine, predicate=predicate,
                             options=options)

    @staticmethod
    def read_metadata(source) -> ParquetMetadata:
        return read_metadata(source)

    @staticmethod
    def stream_content_to_strings(source) -> Iterator[List[str]]:
        """Debug reader: every row becomes ["name=value", ...] in column
        order (``streamContentToStrings``, :86-107)."""

        class _StringsHydrator(Hydrator):
            def __init__(self, n):
                self._n = n

            def start(self):
                return []

            def add(self, target, heading, value):
                target.append(f"{heading}={'null' if value is None else value}")
                return target

            def finish(self, target):
                return target

        def supplier(columns):
            return _StringsHydrator(len(columns))

        return ParquetReader.stream_content(source, supplier, None)


class _DatasetIterator:
    """Row stream over a list of files, one open file at a time.

    The first file's schema is the dataset contract: every later file
    must present identical column paths and physical types (checked at
    the file boundary, before any of its rows are yielded).
    """

    def __init__(self, sources, hydrator_supplier, columns, engine, predicate,
                 options: Optional[ReaderOptions] = None):
        if not sources:
            raise ValueError("dataset stream needs at least one source")
        self._sources = sources
        self._supplier = hydrator_supplier
        self._columns = columns
        self._engine = engine
        self._predicate = predicate
        self._options = options
        self._i = 0
        self._schema_state: dict = {}
        self._current: Optional[_ClosingIterator] = None
        self._closed = False
        self._last_meta: Optional[ParquetMetadata] = None
        self._last_columns = None

    def _open_next(self) -> bool:
        if self._i >= len(self._sources):
            return False
        reader = ParquetReader(
            self._sources[self._i], self._supplier, self._columns,
            engine=self._engine, predicate=self._predicate,
            options=self._options,
        )
        try:
            _check_dataset_schema(
                self._schema_state, reader._reader.schema, self._i
            )
        except ValueError:
            reader.close()
            raise
        self._current = _ClosingIterator(reader)
        # retained past close/exhaustion so metadata/columns keep working,
        # matching the single-file iterator (whose footer stays cached)
        self._last_meta = reader.metadata
        self._last_columns = reader.columns
        self._last_report = reader.salvage_report
        self._i += 1
        return True

    @property
    def salvage_report(self):
        """SalvageReport of the file currently (or most recently)
        streaming — reports are per-file; inspect at file boundaries."""
        return getattr(self, "_last_report", None)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._closed:
                raise StopIteration
            if self._current is None and not self._open_next():
                self._closed = True
                raise StopIteration
            try:
                return next(self._current)
            except StopIteration:
                self._current = None  # advance to the next file

    def close(self):
        if not self._closed:
            self._closed = True
            if self._current is not None:
                self._current.close()
                self._current = None

    # surface parity with _ClosingIterator: delegate to the open file;
    # after exhaustion/close, the most recently opened file's footer is
    # retained (the single-file iterator likewise serves its cached
    # footer after close)
    @property
    def metadata(self) -> ParquetMetadata:
        if self._current is None and not self._closed:
            self._open_next()
        if self._current is not None:
            return self._current.metadata
        if self._last_meta is not None:
            return self._last_meta
        raise ValueError("dataset stream is closed")

    @property
    def columns(self):
        if self._current is None and not self._closed:
            self._open_next()
        if self._current is not None:
            return self._current.columns
        if self._last_columns is not None:
            return self._last_columns
        raise ValueError("dataset stream is closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ScanRowIterator:
    """Row stream over a scan-scheduled dataset (``docs/scan.md``): the
    same rows, order, null semantics, and error wrapping as
    ``_DatasetIterator``, but row groups are read (coalesced, vectored)
    and decoded across files ahead of the consumer by
    ``scan.DatasetScanner``.  Under ``ReaderOptions(salvage=True)`` the
    scanner's per-unit quarantines serve ``None`` cells for quarantined
    columns (the sequential row face's contract) and
    ``salvage_report`` exposes the DATASET-level fold (per-unit reports
    merged in delivery order — unlike the sequential dataset iterator's
    per-file reports)."""

    def __init__(self, sources, hydrator_supplier, columns, predicate,
                 options, scan):
        from ..scan import DatasetScanner

        self._scanner = DatasetScanner(
            sources, columns=columns, options=options, scan=scan,
            predicate=predicate,
        )
        self._supplier = hydrator_supplier
        self.hydrator: Optional[Hydrator] = None
        self._hyd_fi = -1  # file the current hydrator was built for
        self._cursors: Optional[List[_ColumnCursor]] = None
        self._rows = 0
        self._row = 0
        self._closed = False

    @property
    def columns(self):
        """Selected descriptors of the first file (opened on demand —
        the sequential dataset iterator's surface)."""
        return self._scanner.columns

    @property
    def metadata(self) -> ParquetMetadata:
        """Footer of the most recently streamed file (the first file
        before any row) — parity with ``_DatasetIterator.metadata``."""
        return self._scanner.metadata

    def __iter__(self):
        return self

    def _advance(self) -> None:
        unit = next(self._scanner)  # StopIteration ends the stream
        if self._hyd_fi != unit.file_index:
            # one supplier call PER FILE — the sequential dataset stream
            # builds a fresh hydrator per file (stateful suppliers
            # observe the call count), and the scan stream must match
            self.hydrator = supplier_of(self._supplier).get(
                self._scanner.columns
            )
            self._hyd_fi = unit.file_index
        self._cursors = _ordered_cursors(
            self._scanner.columns, unit.batch,
            quarantined=_unit_quarantined_rule(unit),
        )
        self._rows = unit.batch.num_rows
        self._row = 0

    def __next__(self):
        try:
            if self._closed:
                raise StopIteration
            while self._cursors is None or self._row >= self._rows:
                self._advance()  # loops past zero-row groups
            h = self.hydrator
            record = h.start()
            i = self._row
            for cursor in self._cursors:
                record = h.add(record, cursor.desc.path[0], cursor.cell(i))
            self._row += 1
            return h.finish(record)
        except StopIteration:
            self.close()
            raise
        except Exception as e:  # floorlint: disable=FL-EXC001
            # Parity: every iteration failure wraps as RuntimeError (the
            # single-file iterator's pinned contract) — EXCEPT
            # file-boundary errors (schema mismatch, a later file's
            # corrupt footer or missing path), which the sequential
            # stream raises BARE from its per-file open; the scanner
            # tags those (pftpu_scan_planning).  Close FIRST so the
            # scan worker pool never outlives the error.
            from ..scan.executor import DatasetSchemaError

            self.close()
            if isinstance(e, DatasetSchemaError) or \
                    getattr(e, "pftpu_scan_planning", False):
                raise
            raise RuntimeError("Failed to read parquet") from e

    @property
    def salvage_report(self):
        """Dataset-level :class:`SalvageReport` fold (None unless
        ``ReaderOptions(salvage=True)``); survives close."""
        return self._scanner.salvage_report

    def report(self):
        """The scan's health summary
        (:class:`~parquet_floor_tpu.utils.trace.ScanReport`), from the
        tracer scope the stream was created under — empty unless that
        scope (or the global tracer) is enabled; see
        ``docs/observability.md``."""
        return self._scanner.report()

    def close(self):
        if not self._closed:
            self._closed = True
            self._scanner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ClosingIterator:
    """Iterator wrapper that closes the reader when exhausted or closed.

    Close failures during cleanup are suppressed (parity with
    ``closeSilently``, :133-139) but real read errors propagate.
    """

    def __init__(self, reader: ParquetReader):
        self._reader = reader
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._reader)
        except StopIteration:
            self.close()
            raise

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self._reader.close()
            except Exception:
                pass

    @property
    def metadata(self) -> ParquetMetadata:
        return self._reader.metadata

    @property
    def columns(self):
        return self._reader.columns

    @property
    def salvage_report(self):
        """SalvageReport of the wrapped reader (kept past exhaustion /
        close, so callers can account for losses after streaming)."""
        return self._reader.salvage_report

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
