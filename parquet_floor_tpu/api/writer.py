"""Declarative writer — L4 parity with the reference's ``ParquetWriter``
(``ParquetWriter.java``), buffering rows columnar and flushing row groups
through the from-scratch engine.

Parity surface:
  * ``write_file`` static verb — ``writeFile`` (:26-55)
  * instance ``write`` / ``close`` — (:70-77)
  * pinned defaults SNAPPY + v2 pages — (:65-66)
  * Dehydrator → ValueWriter(name, value) plumbing — (:108-135)
  * per-field type switch accepting INT32/INT64/DOUBLE/BOOLEAN/FLOAT and
    BINARY only when annotated as UTF-8 string; everything else rejected —
    (:142-164).  The engine below supports more (bytes, FLBA, INT96,
    nested), mirroring the reference's facade-strict/engine-capable split.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional

import numpy as np

from ..format.file_write import (
    ParquetFileWriter,
    WriterOptions,
    make_column_data,
)
from ..format.parquet_thrift import CompressionCodec, Type
from ..format.schema import MessageType
from .hydrate import Dehydrator, ValueWriter


class _RowValueWriter(ValueWriter):
    """Collects (name, value) pairs for the current row with the reference's
    type-checking semantics (``writeField``, :142-164)."""

    __slots__ = ("schema", "slots")

    def __init__(self, schema: MessageType):
        self.schema = schema
        self.slots: Optional[list] = None

    def write(self, name: str, value: Any) -> None:
        idx = self.schema.field_index(name)  # name→index per call (parity :143)
        field = self.schema.fields[idx]
        pt = field.physical_type
        if pt == Type.INT32 or pt == Type.INT64:
            if isinstance(value, bool) or not isinstance(value, (int, np.integer)):
                raise ValueError(self._type_error(field, value))
        elif pt == Type.DOUBLE or pt == Type.FLOAT:
            if (not isinstance(value, (float, int, np.floating, np.integer))
                    or isinstance(value, bool)):
                raise ValueError(self._type_error(field, value))
        elif pt == Type.BOOLEAN:
            if not isinstance(value, (bool, np.bool_)):
                raise ValueError(self._type_error(field, value))
        elif pt == Type.BYTE_ARRAY:
            lt = field.logical_type
            if lt is None or lt.kind != "STRING" or not isinstance(value, str):
                raise ValueError(self._type_error(field, value))
        else:
            raise ValueError(self._type_error(field, value))
        self.slots[idx] = value

    @staticmethod
    def _type_error(field, value) -> str:
        return (
            f"Cannot write value of type {type(value).__name__} "
            f"to field {field!r}"
        )


class ParquetWriter:
    """Row-at-a-time writer over columnar row-group buffers."""

    def __init__(self, schema: MessageType, dest, dehydrator: Dehydrator,
                 options: Optional[WriterOptions] = None):
        if not all(f.is_primitive for f in schema.fields):
            raise ValueError("ParquetWriter facade supports flat schemas only")
        # Pinned defaults: SNAPPY codec, v2 pages (parity :65-66).
        self.options = options or WriterOptions(
            codec=CompressionCodec.SNAPPY, page_version=2
        )
        self.schema = schema
        self.dehydrator = dehydrator
        if self.options.engine != "host":
            # the facade rides the device encode engine
            # (docs/write.md): row groups flush through the fused
            # encode launches + the encode‖compress‖write pipeline
            from ..write.encode import resolve_writer

            self._writer = resolve_writer(dest, schema, self.options)
        else:
            self._writer = ParquetFileWriter(dest, schema, self.options)
        self._vw = _RowValueWriter(schema)
        self._buffer: List[list] = []
        self._buffer_bytes = 0
        self._closed = False

    @staticmethod
    def _row_bytes(slots) -> int:
        """Rough in-memory size of one buffered row (the row_group_bytes
        flush estimate — mirrors parquet-mr's memory-size block check)."""
        total = 0
        for v in slots:
            if v is None:
                total += 1
            elif isinstance(v, str):
                # byte estimate, not character count: non-ASCII text would
                # otherwise systematically under-count and flush late
                total += (
                    len(v) if v.isascii() else len(v.encode("utf-8"))
                ) + 4
            elif isinstance(v, bytes):
                total += len(v) + 4
            else:
                total += 8
        return total

    def write(self, record: Any) -> None:
        """Dehydrate and buffer one record (``write``, :70-72)."""
        if self._closed:
            raise ValueError("writer is closed")
        self._vw.slots = [None] * len(self.schema.fields)
        self.dehydrator.dehydrate(record, self._vw)
        self._buffer.append(self._vw.slots)
        gb = self.options.row_group_bytes
        if gb:
            self._buffer_bytes += self._row_bytes(self._vw.slots)
        self._vw.slots = None
        if len(self._buffer) >= self.options.row_group_rows or (
            gb and self._buffer_bytes >= gb
        ):
            self._flush()

    def _flush(self) -> None:
        if not self._buffer:
            return
        columns = []
        rows = self._buffer
        for i, desc in enumerate(self.schema.columns):
            col = [row[i] for row in rows]
            if desc.max_definition_level == 0 and any(v is None for v in col):
                raise ValueError(
                    f"required field {desc.path[0]!r} missing in some records"
                )
            columns.append(make_column_data(desc, col))
        self._writer.write_row_group(columns)
        self._buffer = []
        self._buffer_bytes = 0

    def close(self) -> None:
        if not self._closed:
            self._flush()
            self._writer.close()
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.close()
        else:
            # don't finalize a footer over partial data, but release the file
            self._closed = True
            self._writer.abort()

    # -- static verbs (reference API) --------------------------------------

    @staticmethod
    def write_file(schema: MessageType, dest, dehydrator: Dehydrator,
                   records: Iterable[Any],
                   options: Optional[WriterOptions] = None) -> None:
        """Write all records and close (``writeFile``, :26-55)."""
        with ParquetWriter(schema, dest, dehydrator, options) as w:
            for r in records:
                w.write(r)
