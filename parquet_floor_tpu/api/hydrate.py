"""Hydrator/Dehydrator plugin boundary — parity with the reference's
``blue.strategic.parquet`` interfaces (``Hydrator.java:12-28``,
``HydratorSupplier.java:10-19``, ``Dehydrator.java:13``,
``ValueWriter.java:3-5``), expressed as Python protocols.

Duck typing applies throughout: anything with matching methods works; the
ABCs here are optional convenience bases.  ``HydratorSupplier.constantly``
and function-based adapters are provided for ergonomic parity.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Generic, List, TypeVar

from ..format.schema import ColumnDescriptor

U = TypeVar("U")  # mutable hydration target
S = TypeVar("S")  # sealed record
T = TypeVar("T")  # record being dehydrated


class Hydrator(ABC, Generic[U, S]):
    """Builds a domain object from one row's cells.

    Contract (parity with ``Hydrator.java``): ``start()`` creates a mutable
    target; ``add(target, heading, value)`` applies one cell (``value`` is
    None for null cells) and returns the (possibly new) target; ``finish``
    seals it.
    """

    @abstractmethod
    def start(self) -> U: ...

    @abstractmethod
    def add(self, target: U, heading: str, value: Any) -> U: ...

    @abstractmethod
    def finish(self, target: U) -> S: ...


class HydratorSupplier(ABC, Generic[U, S]):
    """Factory receiving the projected columns.

    Values will always be added to the hydrator in the same order as the
    columns supplied here (``HydratorSupplier.java:10-15``).
    """

    @abstractmethod
    def get(self, columns: List[ColumnDescriptor]) -> Hydrator[U, S]: ...

    @staticmethod
    def constantly(hydrator: Hydrator[U, S]) -> "HydratorSupplier[U, S]":
        class _Const(HydratorSupplier):
            def get(self, columns):
                return hydrator

        return _Const()


class BatchHydrator(ABC, Generic[S]):
    """The BATCH face of the Hydrator boundary (no reference
    counterpart at this granularity — the native win SURVEY.md §7 L3
    names): instead of one ``add`` call per cell, the plugin receives
    one call per ROW GROUP with every projected column as an array.

    Ordering contract (parity with ``HydratorSupplier.java:10-15``):
    columns arrive in the same order as the descriptors supplied to
    ``BatchHydratorSupplier.get``.  Arrays are ``batch.columns
    .BatchColumn`` — NumPy from the host engine, device-resident
    ``jax.Array`` from the TPU engine (no device→host copy unless the
    plugin asks for one).
    """

    @abstractmethod
    def batch(self, group_index: int, columns: List[Any]) -> S:
        """Consume one row group; returns the hydrated batch."""


class BatchHydratorSupplier(ABC, Generic[S]):
    """Factory receiving the projected columns (ordering contract as
    ``HydratorSupplier``)."""

    @abstractmethod
    def get(self, columns: List[ColumnDescriptor]) -> BatchHydrator[S]: ...

    @staticmethod
    def constantly(hydrator: BatchHydrator[S]) -> "BatchHydratorSupplier[S]":
        class _Const(BatchHydratorSupplier):
            def get(self, columns):
                return hydrator

        return _Const()


class FnBatchHydrator(BatchHydrator):
    def __init__(self, fn: Callable[[int, List[Any]], Any]):
        self._fn = fn

    def batch(self, group_index, columns):
        return self._fn(group_index, columns)


def batch_supplier_of(obj) -> BatchHydratorSupplier:
    """Coerce a BatchHydrator / supplier / callable / None into a
    supplier.  ``None`` → identity (yield the ``BatchColumn`` lists)."""
    if obj is None:
        return BatchHydratorSupplier.constantly(
            FnBatchHydrator(lambda gi, cols: cols)
        )
    if isinstance(obj, BatchHydratorSupplier):
        return obj
    if isinstance(obj, BatchHydrator):
        return BatchHydratorSupplier.constantly(obj)
    if callable(obj):
        class _Fn(BatchHydratorSupplier):
            def get(self, columns):
                made = obj(columns)
                # duck typing first (module contract: anything with a
                # .batch method IS a hydrator, ABC or not — and it wins
                # over __call__ for objects that are both)
                if hasattr(made, "batch"):
                    return made
                if callable(made):
                    # a supplier returning a per-batch FUNCTION: the
                    # natural "factory of callables" shape — wrap it
                    # rather than failing later with an opaque
                    # AttributeError on .batch
                    return FnBatchHydrator(made)
                raise TypeError(
                    "batch hydrator factory returned "
                    f"{type(made).__name__}; expected a BatchHydrator "
                    "or a (group_index, columns) callable.  Accepted "
                    "callable shapes: columns -> BatchHydrator, or "
                    "columns -> ((group_index, columns) -> Any)"
                )

        return _Fn()
    raise TypeError(
        f"cannot make a BatchHydratorSupplier from {type(obj).__name__}"
    )


class Dehydrator(ABC, Generic[T]):
    """Writes one record's fields through a ValueWriter (``Dehydrator.java:13``)."""

    @abstractmethod
    def dehydrate(self, record: T, value_writer: "ValueWriter") -> None: ...


class ValueWriter(ABC):
    """Single-method callback (``ValueWriter.java:3-5``)."""

    @abstractmethod
    def write(self, name: str, value: Any) -> None: ...


# ---------------------------------------------------------------------------
# Function adapters (Python-idiomatic sugar; no reference counterpart needed)
# ---------------------------------------------------------------------------

class FnHydrator(Hydrator):
    def __init__(self, start: Callable[[], Any], add: Callable[[Any, str, Any], Any],
                 finish: Callable[[Any], Any]):
        self._start, self._add, self._finish = start, add, finish

    def start(self):
        return self._start()

    def add(self, target, heading, value):
        return self._add(target, heading, value)

    def finish(self, target):
        return self._finish(target)


class FnDehydrator(Dehydrator):
    def __init__(self, fn: Callable[[Any, ValueWriter], None]):
        self._fn = fn

    def dehydrate(self, record, value_writer):
        self._fn(record, value_writer)


def dict_hydrator() -> Hydrator:
    """Hydrate rows into plain dicts (common case; used by tests/benchmarks)."""
    return FnHydrator(
        start=dict,
        add=lambda d, heading, value: (d.__setitem__(heading, value), d)[1],
        finish=lambda d: d,
    )


def dict_dehydrator() -> Dehydrator:
    """Dehydrate mapping records by writing every (key, value) pair."""

    def fn(record, vw):
        for k, v in record.items():
            vw.write(k, v)

    return FnDehydrator(fn)


def supplier_of(obj) -> HydratorSupplier:
    """Coerce a Hydrator / HydratorSupplier / callable into a supplier."""
    if isinstance(obj, HydratorSupplier):
        return obj
    if isinstance(obj, Hydrator):
        return HydratorSupplier.constantly(obj)
    if callable(obj):
        class _Fn(HydratorSupplier):
            def get(self, columns):
                made = obj(columns)
                # same diagnostic as batch_supplier_of: fail HERE with
                # the accepted shape, not later with an opaque
                # AttributeError on .start deep in the read loop.
                # Duck typing: start/add/finish is the contract, the
                # ABC is optional
                if all(
                    hasattr(made, m) for m in ("start", "add", "finish")
                ):
                    return made
                raise TypeError(
                    f"hydrator factory returned {type(made).__name__}; "
                    "expected an object with start()/add()/finish() "
                    "(Hydrator protocol) — the factory shape is "
                    "columns -> Hydrator"
                )

        return _Fn()
    raise TypeError(f"cannot make a HydratorSupplier from {type(obj).__name__}")
