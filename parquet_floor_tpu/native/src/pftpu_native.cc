// pftpu_native: host-side hot loops for parquet-floor-tpu.
//
// TPU-native replacement for the JNI-wrapped codec natives the reference
// consumes transitively (SURVEY.md §2.4: snappy-java/libsnappy behind the
// io.compress shim seam).  Implemented from scratch against the public
// Snappy block-format description and the Parquet RLE/bit-packed hybrid
// spec.  Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: parquet_floor_tpu/native/build.sh  (g++ -O3 -shared -fPIC)

#include <cstddef>
#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Snappy block format
// ---------------------------------------------------------------------------

static inline size_t varint_encode(size_t n, uint8_t* out) {
  size_t i = 0;
  while (n >= 0x80) {
    out[i++] = static_cast<uint8_t>(n) | 0x80;
    n >>= 7;
  }
  out[i++] = static_cast<uint8_t>(n);
  return i;
}

static inline ptrdiff_t varint_decode(const uint8_t* p, const uint8_t* end,
                                      uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  const uint8_t* start = p;
  while (p < end && shift <= 35) {
    uint8_t b = *p++;
    result |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return p - start;
    }
    shift += 7;
  }
  return -1;
}

// Full-width variant for DELTA_BINARY_PACKED headers (first_value and
// min_delta are 64-bit zigzags, up to 10 bytes).  Varints carrying bits
// past 2^63 are nonconforming; reporting them malformed (-1) routes the
// column to the host decoder, whose unbounded-precision walk defines the
// semantics — decoded values agree with or without the native library
// (the Python walk wraps such varints via _wrap64 and may keep the
// device path instead; only the path choice differs, not the values).
static inline ptrdiff_t varint_decode64(const uint8_t* p, const uint8_t* end,
                                        uint64_t* out) {
  uint64_t result = 0;
  int shift = 0;
  const uint8_t* start = p;
  while (p < end && shift <= 63) {
    const uint8_t b = *p++;
    const uint64_t payload = b & 0x7F;
    if (shift == 63 && (payload >> 1)) return -1;  // bits past 2^63
    result |= payload << shift;
    if (!(b & 0x80)) {
      *out = result;
      return p - start;
    }
    shift += 7;
  }
  return -1;
}

size_t pftpu_snappy_max_compressed_size(size_t n) {
  // worst case: all literals + tag overhead + length varint
  return 32 + n + n / 6;
}

ptrdiff_t pftpu_snappy_uncompressed_size(const uint8_t* src, size_t src_len) {
  uint64_t n;
  ptrdiff_t used = varint_decode(src, src + src_len, &n);
  if (used < 0) return -1;
  return static_cast<ptrdiff_t>(n);
}

// --- compression (greedy hash matcher, 14-bit table) -----------------------

static const int kHashBits = 14;
static const size_t kHashSize = 1u << kHashBits;

static inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint32_t hash32(uint32_t v) {
  return (v * 0x1E35A7BDu) >> (32 - kHashBits);
}

static inline uint8_t* emit_literal(uint8_t* dst, const uint8_t* src,
                                    size_t len) {
  size_t n = len - 1;
  if (n < 60) {
    *dst++ = static_cast<uint8_t>(n << 2);
  } else if (n < (1u << 8)) {
    *dst++ = 60 << 2;
    *dst++ = static_cast<uint8_t>(n);
  } else if (n < (1u << 16)) {
    *dst++ = 61 << 2;
    *dst++ = static_cast<uint8_t>(n);
    *dst++ = static_cast<uint8_t>(n >> 8);
  } else if (n < (1u << 24)) {
    *dst++ = 62 << 2;
    *dst++ = static_cast<uint8_t>(n);
    *dst++ = static_cast<uint8_t>(n >> 8);
    *dst++ = static_cast<uint8_t>(n >> 16);
  } else {
    *dst++ = 63 << 2;
    *dst++ = static_cast<uint8_t>(n);
    *dst++ = static_cast<uint8_t>(n >> 8);
    *dst++ = static_cast<uint8_t>(n >> 16);
    *dst++ = static_cast<uint8_t>(n >> 24);
  }
  std::memcpy(dst, src, len);
  return dst + len;
}

static inline uint8_t* emit_copy_upto64(uint8_t* dst, size_t offset,
                                        size_t len) {
  if (len >= 4 && len <= 11 && offset < 2048) {
    *dst++ = static_cast<uint8_t>(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
    *dst++ = static_cast<uint8_t>(offset);
  } else if (offset < (1u << 16)) {
    *dst++ = static_cast<uint8_t>(2 | ((len - 1) << 2));
    *dst++ = static_cast<uint8_t>(offset);
    *dst++ = static_cast<uint8_t>(offset >> 8);
  } else {
    *dst++ = static_cast<uint8_t>(3 | ((len - 1) << 2));
    *dst++ = static_cast<uint8_t>(offset);
    *dst++ = static_cast<uint8_t>(offset >> 8);
    *dst++ = static_cast<uint8_t>(offset >> 16);
    *dst++ = static_cast<uint8_t>(offset >> 24);
  }
  return dst;
}

static inline uint8_t* emit_copy(uint8_t* dst, size_t offset, size_t len) {
  while (len >= 68) {
    dst = emit_copy_upto64(dst, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    dst = emit_copy_upto64(dst, offset, len - 60);
    len = 60;
  }
  return emit_copy_upto64(dst, offset, len);
}

ptrdiff_t pftpu_snappy_compress(const uint8_t* src, size_t src_len,
                                uint8_t* dst, size_t dst_cap) {
  if (dst_cap < pftpu_snappy_max_compressed_size(src_len)) return -1;
  uint8_t* out = dst;
  out += varint_encode(src_len, out);
  if (src_len < 16) {
    if (src_len) out = emit_literal(out, src, src_len);
    return out - dst;
  }
  uint16_t table[kHashSize];
  std::memset(table, 0, sizeof(table));
  // table stores pos+1 within the current 64KB-ish window base
  size_t pos = 0, lit_start = 0;
  const size_t limit = src_len - 4;
  size_t base = 0;  // window base so uint16 entries stay valid
  while (pos <= limit) {
    if (pos - base >= 60000) {  // rebase the window
      base = pos;
      std::memset(table, 0, sizeof(table));
    }
    uint32_t h = hash32(load32(src + pos));
    size_t cand = base + table[h];
    table[h] = static_cast<uint16_t>(pos - base + 1);
    // cand==base means empty slot (stored value 0) unless a real match at
    // base+? ; offset by one to disambiguate
    if (cand == base) {
      pos++;
      continue;
    }
    cand -= 1;
    size_t offset = pos - cand;
    if (offset == 0 || offset >= (1u << 16) ||
        load32(src + cand) != load32(src + pos)) {
      pos++;
      continue;
    }
    size_t mlen = 4;
    const size_t maxm = src_len - pos;
    while (mlen < maxm && src[cand + mlen] == src[pos + mlen]) mlen++;
    if (lit_start < pos) out = emit_literal(out, src + lit_start, pos - lit_start);
    out = emit_copy(out, offset, mlen);
    pos += mlen;
    lit_start = pos;
  }
  if (lit_start < src_len)
    out = emit_literal(out, src + lit_start, src_len - lit_start);
  return out - dst;
}

ptrdiff_t pftpu_snappy_decompress(const uint8_t* src, size_t src_len,
                                  uint8_t* dst, size_t dst_cap) {
  uint64_t expected;
  ptrdiff_t used = varint_decode(src, src + src_len, &expected);
  if (used < 0 || expected > dst_cap) return -1;
  const uint8_t* p = src + used;
  const uint8_t* end = src + src_len;
  uint8_t* out = dst;
  uint8_t* out_end = dst + expected;
  while (p < end) {
    const uint8_t tag = *p++;
    const int kind = tag & 3;
    if (kind == 0) {  // literal
      size_t len = tag >> 2;
      if (len >= 60) {
        const size_t nb = len - 59;
        if (p + nb > end) return -2;
        len = 0;
        for (size_t i = 0; i < nb; i++) len |= static_cast<size_t>(p[i]) << (8 * i);
        p += nb;
      }
      len += 1;
      if (p + len > end || out + len > out_end) return -2;
      std::memcpy(out, p, len);
      p += len;
      out += len;
      continue;
    }
    size_t len, offset;
    if (kind == 1) {
      if (p + 1 > end) return -2;
      len = ((tag >> 2) & 0x7) + 4;
      offset = (static_cast<size_t>(tag >> 5) << 8) | *p++;
    } else if (kind == 2) {
      if (p + 2 > end) return -2;
      len = (tag >> 2) + 1;
      offset = p[0] | (static_cast<size_t>(p[1]) << 8);
      p += 2;
    } else {
      if (p + 4 > end) return -2;
      len = (tag >> 2) + 1;
      offset = p[0] | (static_cast<size_t>(p[1]) << 8) |
               (static_cast<size_t>(p[2]) << 16) |
               (static_cast<size_t>(p[3]) << 24);
      p += 4;
    }
    if (offset == 0 || offset > static_cast<size_t>(out - dst)) return -2;
    if (out + len > out_end) return -2;
    const uint8_t* from = out - offset;
    if (offset >= len) {
      std::memcpy(out, from, len);
      out += len;
    } else {
      for (size_t i = 0; i < len; i++) *out++ = *from++;
    }
  }
  if (out != out_end) return -2;
  return out - dst;
}

// ---------------------------------------------------------------------------
// LZ4 raw block decode (parquet LZ4_RAW, and the payload of Hadoop-framed
// LZ4).  Sequence copies must go byte-by-byte when overlapping (RLE-style
// offsets < length are the common case).
// ---------------------------------------------------------------------------

ptrdiff_t pftpu_lz4_decompress(const uint8_t* src, size_t src_len,
                               uint8_t* dst, size_t dst_cap) {
  const uint8_t* p = src;
  const uint8_t* const end = src + src_len;
  uint8_t* out = dst;
  uint8_t* const out_end = dst + dst_cap;
  while (p < end) {
    const uint8_t token = *p++;
    size_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (p >= end) return -1;
        b = *p++;
        lit += b;
      } while (b == 255);
    }
    if (lit > static_cast<size_t>(end - p)) return -1;
    if (lit > static_cast<size_t>(out_end - out)) return -2;
    std::memcpy(out, p, lit);
    p += lit;
    out += lit;
    if (p >= end) break;  // final sequence carries literals only
    if (p + 2 > end) return -1;
    const size_t offset = static_cast<size_t>(p[0]) | (static_cast<size_t>(p[1]) << 8);
    p += 2;
    if (offset == 0 || offset > static_cast<size_t>(out - dst)) return -1;
    size_t mlen = token & 0xF;
    if (mlen == 15) {
      uint8_t b;
      do {
        if (p >= end) return -1;
        b = *p++;
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    if (mlen > static_cast<size_t>(out_end - out)) return -2;
    const uint8_t* from = out - offset;
    if (offset >= mlen) {
      std::memcpy(out, from, mlen);
      out += mlen;
    } else {
      for (size_t i = 0; i < mlen; i++) *out++ = *from++;
    }
  }
  return out - dst;
}

// ---------------------------------------------------------------------------
// RLE/bit-packed hybrid run-table parse (phase 1 of the two-phase decode;
// phase 2 — expansion — runs vectorized on TPU or in NumPy)
// ---------------------------------------------------------------------------

// Row layout matches format/encodings/rle_hybrid.py parse_runs:
//   [kind(0=RLE,1=bitpacked), count, value_or_byte_offset, 0]
ptrdiff_t pftpu_rle_parse_runs(const uint8_t* data, size_t data_len,
                               long long num_values, int bit_width,
                               long long* out_table, size_t cap_rows,
                               long long* end_pos) {
  if (bit_width == 0) {
    *end_pos = 0;
    return 0;
  }
  const uint8_t* p = data;
  const uint8_t* end = data + data_len;
  long long remaining = num_values;
  const int value_bytes = (bit_width + 7) / 8;
  size_t rows = 0;
  while (remaining > 0) {
    uint64_t header;
    ptrdiff_t used = varint_decode(p, end, &header);
    if (used < 0) return -1;
    p += used;
    if (header & 1) {
      const long long groups = static_cast<long long>(header >> 1);
      // hostile/corrupt headers: groups * bit_width must not overflow, and
      // a run can never legitimately exceed the remaining byte budget
      if (groups < 0 || groups > static_cast<long long>(data_len)) return -1;
      const long long n = groups * 8;
      if (rows >= cap_rows) return -2;
      out_table[rows * 4 + 0] = 1;
      out_table[rows * 4 + 1] = n < remaining ? n : remaining;
      out_table[rows * 4 + 2] = p - data;
      out_table[rows * 4 + 3] = 0;
      rows++;
      const long long nbytes = groups * bit_width;
      if (p + nbytes > end) return -1;
      p += nbytes;
      remaining -= n;
    } else {
      const long long n = static_cast<long long>(header >> 1);
      if (n < 0) return -1;  // 64-bit varint overflow in a hostile header
      if (p + value_bytes > end) return -1;
      long long value = 0;
      for (int i = 0; i < value_bytes; i++)
        value |= static_cast<long long>(p[i]) << (8 * i);
      p += value_bytes;
      if (rows >= cap_rows) return -2;
      out_table[rows * 4 + 0] = 0;
      out_table[rows * 4 + 1] = n < remaining ? n : remaining;
      out_table[rows * 4 + 2] = value;
      out_table[rows * 4 + 3] = 0;
      rows++;
      remaining -= n;
    }
  }
  *end_pos = p - data;
  return static_cast<ptrdiff_t>(rows);
}

// Parse many independent RLE/bit-packed streams of ONE buffer in a single
// call (staging parses one stream per page per level/index category — the
// per-call overhead of crossing the C boundary dominated the work).  For
// stream s: counts[s] values at bws[s] bits starting at data+pos[s].  Run
// rows land contiguously in out_table with byte offsets rebased to be
// absolute in `data`; out_runs[s] = rows of stream s.  Returns total rows,
// -1 on malformed input, -2 when cap_rows is too small.
ptrdiff_t pftpu_rle_parse_runs_batch(const uint8_t* data, size_t data_len,
                                     long long n_streams,
                                     const long long* pos,
                                     const long long* counts,
                                     const long long* bws,
                                     long long* out_table, size_t cap_rows,
                                     long long* out_runs) {
  size_t used = 0;
  for (long long s = 0; s < n_streams; s++) {
    if (pos[s] < 0 || static_cast<size_t>(pos[s]) > data_len) return -1;
    if (bws[s] == 0) {  // mirrors parse_runs: empty table for bw 0
      out_runs[s] = 0;
      continue;
    }
    if (bws[s] < 0 || bws[s] > 64) return -1;
    long long end_pos = 0;
    ptrdiff_t r = pftpu_rle_parse_runs(
        data + pos[s], data_len - static_cast<size_t>(pos[s]), counts[s],
        static_cast<int>(bws[s]), out_table + used * 4, cap_rows - used,
        &end_pos);
    if (r < 0) return r;
    for (ptrdiff_t i = 0; i < r; i++) {
      if (out_table[(used + i) * 4 + 0] == 1)
        out_table[(used + i) * 4 + 2] += pos[s];
    }
    out_runs[s] = r;
    used += static_cast<size_t>(r);
  }
  return static_cast<ptrdiff_t>(used);
}

// Parse many streams straight into the flat 5×pad int32 device plan
// (out_end, kind, value, bytebase, bw) — the fused-decode operand — in
// one pass, skipping the intermediate per-stream run tables and the
// NumPy concat/cumsum/masked-write passes over them.  bws[s] == 0 emits
// one synthetic RLE run of counts[s] zeros (the dictionary zero-width
// page case).  Returns rows used; -1 malformed; -2 pad_runs too small
// (parsing continues without writing so *rows_needed reports the exact
// row count — the caller re-sizes in one retry); -3 run counts don't
// sum to total; -4 int32 overflow (byte offset past 2 GiB or a single
// run past 2^31 within-run bits — PlanOverflow).
ptrdiff_t pftpu_rle_plan5_batch(const uint8_t* data, size_t data_len,
                                long long n_streams,
                                const long long* pos,
                                const long long* counts,
                                const long long* bws,
                                long long total,
                                int32_t* plan, long long pad_runs,
                                long long* rows_needed) {
  int32_t* out_end = plan;
  int32_t* kind = plan + pad_runs;
  int32_t* value = plan + 2 * pad_runs;
  int32_t* bytebase = plan + 3 * pad_runs;
  int32_t* bwrow = plan + 4 * pad_runs;
  long long rows = 0;
  long long cum = 0;
  int overflowed = 0;  // keep counting so *rows_needed is exact
  for (long long s = 0; s < n_streams; s++) {
    if (bws[s] == 0) {
      cum += counts[s];
      if (cum > total) return -3;
      if (rows < pad_runs) {
        kind[rows] = 0;
        value[rows] = 0;
        bytebase[rows] = 0;
        bwrow[rows] = 0;
        out_end[rows] = static_cast<int32_t>(cum);
      } else {
        overflowed = 1;
      }
      rows++;
      continue;
    }
    if (pos[s] < 0 || static_cast<size_t>(pos[s]) > data_len) return -1;
    const uint8_t* p = data + pos[s];
    const uint8_t* end = data + data_len;
    long long remaining = counts[s];
    const int bw = static_cast<int>(bws[s]);
    if (bw < 0 || bw > 64) return -1;
    const int value_bytes = (bw + 7) / 8;
    while (remaining > 0) {
      uint64_t header;
      ptrdiff_t used = varint_decode(p, end, &header);
      if (used < 0) return -1;
      p += used;
      if (header & 1) {
        const long long groups = static_cast<long long>(header >> 1);
        if (groups < 0 || groups > static_cast<long long>(data_len)) return -1;
        const long long n = groups * 8;
        const long long cnt = n < remaining ? n : remaining;
        const long long off = p - data;
        if (off >= (1LL << 31)) return -4;
        if (cnt * bw >= (1LL << 31)) return -4;
        cum += cnt;
        if (cum > total) return -3;
        if (rows < pad_runs) {
          kind[rows] = 1;
          value[rows] = 0;
          bytebase[rows] = static_cast<int32_t>(off);
          bwrow[rows] = bw;
          out_end[rows] = static_cast<int32_t>(cum);
        } else {
          overflowed = 1;
        }
        rows++;
        const long long nbytes = groups * bw;
        if (end - p < nbytes) return -1;
        p += nbytes;
        remaining -= n;
      } else {
        const long long n = static_cast<long long>(header >> 1);
        if (n < 0) return -1;
        if (end - p < value_bytes) return -1;
        long long v = 0;
        for (int i = 0; i < value_bytes; i++)
          v |= static_cast<long long>(p[i]) << (8 * i);
        p += value_bytes;
        const long long cnt = n < remaining ? n : remaining;
        cum += cnt;
        if (cum > total) return -3;
        if (rows < pad_runs) {
          kind[rows] = 0;
          value[rows] = static_cast<int32_t>(v);  // int32 wrap, as astype
          bytebase[rows] = 0;
          bwrow[rows] = bw;
          out_end[rows] = static_cast<int32_t>(cum);
        } else {
          overflowed = 1;
        }
        rows++;
        remaining -= n;
      }
    }
  }
  if (n_streams > 0 && cum != total) return -3;
  *rows_needed = rows;
  if (overflowed) return -2;
  // pad rows: out_end = total (they own no output), everything else 0
  for (long long r = rows; r < pad_runs; r++) {
    out_end[r] = static_cast<int32_t>(total);
    kind[r] = value[r] = bytebase[r] = bwrow[r] = 0;
  }
  return static_cast<ptrdiff_t>(rows);
}

// ---------------------------------------------------------------------------
// DELTA_BINARY_PACKED plan parse (device staging phase 1): the varint/
// miniblock walk that was staging's hottest pure-Python loop on wide
// tables.  Follows tpu/engine.py parse_delta_plan, including the
// interval-arithmetic proof that the int32 device fast path is exact —
// but as a conservative superset-rejecter, not a bit-for-bit mirror: it
// additionally refuses hostile headers the Python walk tolerates
// (n_mini > 2^16, per_mini > 2^24, varints with bits past 2^63 that
// Python wraps via _wrap64).  Rejection only routes the column to the
// authoritative host decoder, so decoded values agree either way; which
// path decodes a malformed stream may differ with/without the library.
// ---------------------------------------------------------------------------

// out_scalars: [first_value, values_per_miniblock, total, end_pos, wide].
// Returns the miniblock count, -1 for malformed-or-unsupported (caller
// falls back to the host decoder), -2 when cap_rows is too small.
ptrdiff_t pftpu_delta_parse_plan(const uint8_t* data, size_t data_len,
                                 int value_bytes, int allow_wide,
                                 long long* mb_byte, long long* mb_bw,
                                 long long* mb_min, size_t cap_rows,
                                 long long* out_scalars) {
  const uint8_t* p = data;
  const uint8_t* end = data + data_len;
  uint64_t block_size, n_mini, total_u, first_u;
  ptrdiff_t u;
  if ((u = varint_decode64(p, end, &block_size)) < 0) return -1;
  p += u;
  if ((u = varint_decode64(p, end, &n_mini)) < 0) return -1;
  p += u;
  if ((u = varint_decode64(p, end, &total_u)) < 0) return -1;
  p += u;
  if ((u = varint_decode64(p, end, &first_u)) < 0) return -1;
  p += u;
  const long long first =
      static_cast<long long>((first_u >> 1) ^ (0ULL - (first_u & 1)));
  if (n_mini == 0 || n_mini > (1u << 16) || block_size % n_mini) return -1;
  const uint64_t per_mini = block_size / n_mini;
  if (per_mini == 0 || per_mini > (1u << 24)) return -1;  // hostile header
  const long long I32MIN = -(1LL << 31), I32MAX = (1LL << 31) - 1;
  const int check_range = value_bytes > 4;
  int wide = (first < I32MIN || first > I32MAX) ? 1 : 0;
  if (wide && !allow_wide) return -1;
  __int128 lo = first, hi = first;  // reachable prefix-sum interval
  const long long total = static_cast<long long>(total_u);
  if (total < 0) return -1;
  const long long n_deltas = total - 1;
  long long got = 0;
  size_t rows = 0;
  while (got < n_deltas) {
    uint64_t md_u;
    if ((u = varint_decode64(p, end, &md_u)) < 0) return -1;
    p += u;
    const long long min_delta =
        static_cast<long long>((md_u >> 1) ^ (0ULL - (md_u & 1)));
    if (min_delta < I32MIN || min_delta > I32MAX) {
      if (!allow_wide) return -1;
      wide = 1;
    }
    if (static_cast<size_t>(end - p) < n_mini) return -1;
    const uint8_t* widths = p;
    p += n_mini;
    for (uint64_t m = 0; m < n_mini && got < n_deltas; m++) {
      const int bwm = widths[m];
      if (bwm > 64) return -1;  // malformed: spec caps deltas at 64 bits
      if (bwm > 32) {
        if (!allow_wide) return -1;
        wide = 1;
      }
      const long long left = n_deltas - got;
      const long long count =
          left < static_cast<long long>(per_mini)
              ? left
              : static_cast<long long>(per_mini);
      if (check_range && !wide) {
        const __int128 d_lo = min_delta;
        const __int128 d_hi =
            static_cast<__int128>(min_delta) +
            ((static_cast<__int128>(1) << bwm) - 1);
        if (d_lo < 0) lo += static_cast<__int128>(count) * d_lo;
        if (d_hi > 0) hi += static_cast<__int128>(count) * d_hi;
        if (lo < I32MIN || hi > I32MAX) {
          if (!allow_wide) return -1;
          wide = 1;
        }
      }
      if (rows >= cap_rows) return -2;
      mb_byte[rows] = p - data;
      mb_bw[rows] = bwm;
      mb_min[rows] = min_delta;
      rows++;
      got += count;
      const long long nbytes =
          static_cast<long long>(per_mini) * bwm / 8;
      if (static_cast<long long>(end - p) < nbytes) return -1;
      p += nbytes;
    }
  }
  out_scalars[0] = first;
  out_scalars[1] = static_cast<long long>(per_mini);
  out_scalars[2] = total;
  out_scalars[3] = p - data;
  out_scalars[4] = wide;
  return static_cast<ptrdiff_t>(rows);
}

// ---------------------------------------------------------------------------
// PLAIN BYTE_ARRAY length-chain walk (the only sequential part of string
// decode; payload gather stays vectorized in NumPy / on device)
// ---------------------------------------------------------------------------

// Writes value payload start offsets and lengths; returns the number of
// values parsed (≤ max_values), or -1 on a malformed chain.
ptrdiff_t pftpu_plain_ba_scan(const uint8_t* data, size_t data_len,
                              long long max_values, long long* out_starts,
                              long long* out_lengths) {
  size_t pos = 0;
  long long n = 0;
  while (pos < data_len && n < max_values) {
    if (pos + 4 > data_len) return -1;
    uint32_t len;
    std::memcpy(&len, data + pos, 4);
    pos += 4;
    if (pos + len > data_len) return -1;
    out_starts[n] = static_cast<long long>(pos);
    out_lengths[n] = static_cast<long long>(len);
    pos += len;
    n++;
  }
  return n;
}

// ---------------------------------------------------------------------------
// First-appearance dedup of byte slices (the writer's dictionary build):
// offsets[n+1] delimit value i as pool[offsets[i]..offsets[i+1]).  Open-
// addressing FNV-1a hash table keyed by slice content; O(n) expected vs
// the NumPy path's padded-key sort.  Writes indices[n] (first-appearance
// rank per value) and uniq_ids (value index of each distinct slice, in
// first-appearance order).  Returns the distinct count, or -1 on
// allocation failure.
// ---------------------------------------------------------------------------

static inline uint64_t pftpu_fnv1a(const uint8_t* p, size_t len) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < len; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

ptrdiff_t pftpu_dedup_bytes(const long long* offsets, size_t n,
                            const uint8_t* pool, uint32_t* indices,
                            long long* uniq_ids) {
  if (n == 0) return 0;
  size_t cap = 16;
  while (cap < n * 2) cap <<= 1;
  long long* table = static_cast<long long*>(
      std::malloc(cap * sizeof(long long)));
  if (table == nullptr) return -1;
  for (size_t i = 0; i < cap; i++) table[i] = -1;
  long long n_uniq = 0;
  const size_t mask = cap - 1;
  for (size_t i = 0; i < n; i++) {
    const uint8_t* p = pool + offsets[i];
    const size_t len = static_cast<size_t>(offsets[i + 1] - offsets[i]);
    size_t slot = static_cast<size_t>(pftpu_fnv1a(p, len)) & mask;
    for (;;) {
      long long j = table[slot];
      if (j < 0) {
        table[slot] = static_cast<long long>(i);
        uniq_ids[n_uniq] = static_cast<long long>(i);
        indices[i] = static_cast<uint32_t>(n_uniq);
        n_uniq++;
        break;
      }
      const size_t jlen =
          static_cast<size_t>(offsets[j + 1] - offsets[j]);
      if (jlen == len && std::memcmp(pool + offsets[j], p, len) == 0) {
        indices[i] = indices[j];
        break;
      }
      slot = (slot + 1) & mask;
    }
  }
  std::free(table);
  return n_uniq;
}

// ---------------------------------------------------------------------------
// RLE/bit-packed hybrid: count decoded values equal to `target` without
// materializing the expansion (definition-level non-null counting — the
// staging hot loop for optional/repeated columns)
// ---------------------------------------------------------------------------

ptrdiff_t pftpu_rle_count_equal(const uint8_t* data, size_t data_len,
                                long long num_values, int bit_width,
                                long long target, long long* out_count) {
  if (bit_width == 0) {
    *out_count = (target == 0) ? num_values : 0;
    return 0;
  }
  const uint8_t* p = data;
  const uint8_t* end = data + data_len;
  long long remaining = num_values;
  const int value_bytes = (bit_width + 7) / 8;
  const uint64_t mask = (bit_width >= 64)
                            ? ~0ULL
                            : ((1ULL << bit_width) - 1);
  long long count = 0;
  while (remaining > 0) {
    uint64_t header;
    ptrdiff_t used = varint_decode(p, end, &header);
    if (used < 0) return -1;
    p += used;
    if (header & 1) {
      const long long groups = static_cast<long long>(header >> 1);
      // hostile/corrupt headers: reject before groups * bit_width can
      // overflow or move the cursor out of bounds
      if (groups < 0 || groups > static_cast<long long>(data_len)) return -1;
      long long n = groups * 8;
      if (n > remaining) n = remaining;
      const long long nbytes = groups * bit_width;
      if (nbytes > end - p) return -1;
      // unpack little-endian bit fields with a rolling 64-bit window
      long long bitpos = 0;
      for (long long i = 0; i < n; i++) {
        const long long byte0 = bitpos >> 3;
        uint64_t window = 0;
        const long long avail = (nbytes - byte0) < 8 ? (nbytes - byte0) : 8;
        std::memcpy(&window, p + byte0, static_cast<size_t>(avail));
        const uint64_t v = (window >> (bitpos & 7)) & mask;
        count += (static_cast<long long>(v) == target);
        bitpos += bit_width;
      }
      p += nbytes;
      remaining -= n;
    } else {
      long long n = static_cast<long long>(header >> 1);
      if (n < 0) return -1;  // 64-bit varint overflow in a hostile header
      if (p + value_bytes > end) return -1;
      long long value = 0;
      for (int i = 0; i < value_bytes; i++)
        value |= static_cast<long long>(p[i]) << (8 * i);
      p += value_bytes;
      if (n > remaining) n = remaining;
      if (value == target) count += n;
      remaining -= n;
    }
  }
  *out_count = count;
  return 0;
}

// ---------------------------------------------------------------------------
// Page-header scan: parse the Thrift compact PageHeader chain of a column
// chunk (the host staging loop's hottest pure-Python cost).  Unknown fields
// (statistics, bloom offsets, …) are skipped structurally.
// ---------------------------------------------------------------------------

namespace {

struct CReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
  int depth = 0;  // skip recursion bound (hostile nesting)

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }
  long long zigzag() {
    uint64_t v = varint();
    return static_cast<long long>((v >> 1) ^ (~(v & 1) + 1));
  }
  void skip_bytes(size_t n) {
    if (static_cast<size_t>(end - p) < n) { ok = false; return; }
    p += n;
  }
  void skip_value(int ctype);
  void skip_struct() {
    if (++depth > 64) { ok = false; return; }  // hostile nesting: bail
    while (ok) {
      if (p >= end) { ok = false; break; }
      uint8_t b = *p++;
      if (b == 0) break;  // STOP
      int ctype = b & 0x0F;
      if (((b >> 4) & 0x0F) == 0) (void)zigzag();  // long-form field id
      skip_value(ctype);
    }
    depth--;
  }
};

void CReader::skip_value(int ctype) {
  // every container path is depth-bounded: hostile nesting must return an
  // error, never exhaust the C stack or spin without consuming input
  if (++depth > 64) { ok = false; return; }
  switch (ctype) {
    case 1: case 2: break;                  // bool in header
    case 3: skip_bytes(1); break;           // byte
    case 4: case 5: case 6: (void)varint(); break;  // i16/i32/i64
    case 7: skip_bytes(8); break;           // double
    case 8: skip_bytes(varint()); break;    // binary
    case 9: case 10: {                      // list/set
      if (p >= end) { ok = false; break; }
      uint8_t h = *p++;
      size_t n = h >> 4;
      int et = h & 0x0F;
      if (n == 15) n = varint();
      for (size_t i = 0; i < n && ok; i++) {
        if (et == 1 || et == 2) skip_bytes(1);  // bool element = 1 byte
        else skip_value(et);
      }
      break;
    }
    case 11: {                              // map
      size_t n = varint();
      if (n) {
        if (p >= end) { ok = false; break; }
        uint8_t kv = *p++;
        int kt = kv >> 4;
        int vt = kv & 0x0F;
        for (size_t i = 0; i < n && ok; i++) {
          // bool elements occupy one byte in containers (skip_value's
          // header-bool path consumes nothing — that would spin forever
          // on a hostile count)
          if (kt == 1 || kt == 2) skip_bytes(1); else skip_value(kt);
          if (vt == 1 || vt == 2) skip_bytes(1); else skip_value(vt);
        }
      }
      break;
    }
    case 12: skip_struct(); break;          // struct
    default: ok = false; break;
  }
  depth--;
}

// Parse one struct, capturing i32/i64/bool fields into slots[fid] when
// fid < cap (slots preinitialized by caller); nested structs are parsed
// recursively only when sub_fid matches, else skipped.
void parse_flat(CReader& r, long long* slots, int cap) {
  int last_fid = 0;
  while (r.ok) {
    if (r.p >= r.end) { r.ok = false; return; }
    uint8_t b = *r.p++;
    if (b == 0) return;
    int ctype = b & 0x0F;
    int delta = (b >> 4) & 0x0F;
    int fid = delta ? last_fid + delta
                    : static_cast<int>(r.zigzag());
    last_fid = fid;
    if (ctype == 1 || ctype == 2) {
      if (fid >= 0 && fid < cap) slots[fid] = (ctype == 1);
      continue;
    }
    if ((ctype >= 4 && ctype <= 6) && fid >= 0 && fid < cap) {
      slots[fid] = r.zigzag();
      continue;
    }
    r.skip_value(ctype);
  }
}

}  // namespace

// Per page, 16 output slots:
//  0 page_type, 1 payload_off, 2 compressed_size, 3 uncompressed_size,
//  4 crc(-1 absent), 5 num_values, 6 encoding, 7 def_enc, 8 rep_enc,
//  9 num_nulls(-1), 10 dl_len(-1), 11 rl_len(-1), 12 is_compressed(-1),
// 13 dict_num_values(-1), 14 dict_encoding(-1), 15 reserved
ptrdiff_t pftpu_split_pages(const uint8_t* data, size_t data_len,
                            long long num_values, long long* out,
                            size_t cap_pages) {
  CReader r{data, data + data_len};
  long long seen = 0;
  size_t n_pages = 0;
  while (seen < num_values && r.p < r.end) {
    if (n_pages >= cap_pages) return -2;
    long long* o = out + n_pages * 16;
    for (int i = 0; i < 16; i++) o[i] = -1;
    // PageHeader fields: 1 type, 2 uncompressed, 3 compressed, 4 crc,
    // 5 data_page_header, 7 dictionary_page_header, 8 data_page_header_v2
    int last_fid = 0;
    bool stop = false;
    while (r.ok && !stop) {
      if (r.p >= r.end) { r.ok = false; break; }
      uint8_t b = *r.p++;
      if (b == 0) { stop = true; break; }
      int ctype = b & 0x0F;
      int delta = (b >> 4) & 0x0F;
      int fid = delta ? last_fid + delta : static_cast<int>(r.zigzag());
      last_fid = fid;
      if (ctype >= 4 && ctype <= 6 && fid >= 1 && fid <= 4) {
        long long v = r.zigzag();
        if (fid == 1) o[0] = v;
        else if (fid == 2) o[3] = v;
        else if (fid == 3) o[2] = v;
        else { o[4] = v; o[15] = 1; }  // crc may be negative: flag presence
        continue;
      }
      if (ctype == 12 && (fid == 5 || fid == 7 || fid == 8)) {
        long long slots[16];
        for (int i = 0; i < 16; i++) slots[i] = -1;
        parse_flat(r, slots, 16);
        if (fid == 5) {           // DataPageHeader: v, enc, def, rep
          o[5] = slots[1]; o[6] = slots[2]; o[7] = slots[3]; o[8] = slots[4];
        } else if (fid == 7) {    // DictionaryPageHeader
          o[13] = slots[1]; o[14] = slots[2];
        } else {                  // DataPageHeaderV2
          o[5] = slots[1]; o[9] = slots[2]; o[6] = slots[4];
          o[10] = slots[5]; o[11] = slots[6]; o[12] = slots[7];
          o[13] = slots[3];  // num_rows (slot shared with dict pages)
        }
        continue;
      }
      r.skip_value(ctype);
    }
    if (!r.ok || o[0] < 0 || o[2] < 0) return -1;
    o[1] = r.p - data;  // payload offset
    if (static_cast<size_t>(o[1]) + static_cast<size_t>(o[2]) > data_len)
      return -1;
    r.p += o[2];
    if (o[0] == 0 || o[0] == 3) {  // DATA_PAGE or DATA_PAGE_V2
      if (o[5] < 0) return -1;
      seen += o[5];
    }
    n_pages++;
  }
  return static_cast<ptrdiff_t>(n_pages);
}

}  // extern "C"
