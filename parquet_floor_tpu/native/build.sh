#!/bin/sh
# Build the native runtime: g++ only, no external deps.
# PFTPU_MARCH defaults to native for local self-builds; CI/distribution
# builds must set a baseline (e.g. x86-64-v2) so the artifact is portable.
set -e
cd "$(dirname "$0")"
g++ -O3 -march="${PFTPU_MARCH:-native}" -fPIC -shared -Wall -Wextra \
    -o libpftpu_native.so src/pftpu_native.cc src/pftpu_zstd.cc
echo "built $(pwd)/libpftpu_native.so"
