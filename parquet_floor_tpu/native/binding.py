"""ctypes binding to the C++ native runtime (libpftpu_native.so).

The native library provides the host-side hot loops that a Python/NumPy
implementation can't make fast: Snappy block compress/decompress and RLE
run-table parsing.  Built from ``parquet_floor_tpu/native/src`` via
``build.sh`` (g++, no external deps).  Everything degrades gracefully to the
pure-Python implementations when the library isn't built.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

from ..errors import checked_alloc_size

_LIB_NAME = "libpftpu_native.so"
_lib = None
_load_attempted = False
_load_lock = threading.Lock()


def _lib_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIB_NAME)


def _try_build() -> bool:
    """Best-effort one-shot build of the native lib (g++, no deps)."""
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        return False
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        subprocess.run(
            ["sh", os.path.join(here, "build.sh")],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def _load():
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    with _load_lock:
        # justified FL-LOCK002 suppression: this is ONE-TIME lazy init.
        # The build (a bounded g++ subprocess) must run exactly once per
        # process and every caller needs its result before proceeding —
        # followers waiting on the lock IS the wanted semantics, and the
        # _load_attempted fast path above means the lock is never taken
        # again once init resolves.  A release-before-wait rewrite would
        # add an Event for zero steady-state benefit.
        return _load_locked()  # floorlint: disable=FL-LOCK002


def _load_locked():
    global _lib, _load_attempted
    if _load_attempted:  # lost the race: another thread finished the load
        return _lib
    path = _lib_path()
    if not os.path.exists(path) and os.environ.get("PFTPU_NO_NATIVE_BUILD") != "1":
        _try_build()
    if not os.path.exists(path):
        _load_attempted = True  # set only once the outcome is final
        return None
    try:
        lib = _register(ctypes.CDLL(path))
        _lib = lib
    except OSError:
        _lib = None
    except AttributeError:
        # stale .so from an older source revision (missing a symbol):
        # rebuild once, retry; degrade to pure Python if that fails too.
        # dlopen caches by pathname (the stale handle is never dlclosed),
        # so the rebuilt library must load from a fresh path.
        _lib = None
        if os.environ.get("PFTPU_NO_NATIVE_BUILD") != "1" and _try_build():
            import shutil
            import tempfile

            try:
                fd, fresh = tempfile.mkstemp(suffix=".so", prefix="pftpu_")
                os.close(fd)
                shutil.copy2(path, fresh)
                _lib = _register(ctypes.CDLL(fresh))
            except (OSError, AttributeError):
                _lib = None
    _load_attempted = True  # after _lib is final, so the lock-free path is safe
    return _lib


def _register(lib):
    """Declare every exported symbol's signature; raises AttributeError when
    the loaded library predates a symbol (stale build)."""
    lib.pftpu_snappy_max_compressed_size.restype = ctypes.c_size_t
    lib.pftpu_snappy_max_compressed_size.argtypes = [ctypes.c_size_t]
    lib.pftpu_snappy_compress.restype = ctypes.c_ssize_t
    lib.pftpu_snappy_compress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.pftpu_snappy_uncompressed_size.restype = ctypes.c_ssize_t
    lib.pftpu_snappy_uncompressed_size.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.pftpu_snappy_decompress.restype = ctypes.c_ssize_t
    lib.pftpu_snappy_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.pftpu_plain_ba_scan.restype = ctypes.c_ssize_t
    lib.pftpu_plain_ba_scan.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.pftpu_zstd_decompress.restype = ctypes.c_ssize_t
    lib.pftpu_zstd_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.pftpu_zstd_max_compressed_size.restype = ctypes.c_size_t
    lib.pftpu_zstd_max_compressed_size.argtypes = [ctypes.c_size_t]
    lib.pftpu_zstd_compress_store.restype = ctypes.c_ssize_t
    lib.pftpu_zstd_compress_store.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.pftpu_rle_parse_runs.restype = ctypes.c_ssize_t
    lib.pftpu_rle_parse_runs.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,  # data
        ctypes.c_longlong, ctypes.c_int,   # num_values, bit_width
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_size_t,  # out table, capacity rows
        ctypes.POINTER(ctypes.c_longlong),  # end position out
    ]
    lib.pftpu_rle_parse_runs_batch.restype = ctypes.c_ssize_t
    lib.pftpu_rle_parse_runs_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # data
        ctypes.c_longlong,                  # n_streams
        ctypes.POINTER(ctypes.c_longlong),  # pos[]
        ctypes.POINTER(ctypes.c_longlong),  # counts[]
        ctypes.POINTER(ctypes.c_longlong),  # bws[]
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_size_t,  # out table, cap
        ctypes.POINTER(ctypes.c_longlong),  # out_runs[]
    ]
    lib.pftpu_rle_plan5_batch.restype = ctypes.c_ssize_t
    lib.pftpu_rle_plan5_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # data
        ctypes.c_longlong,                  # n_streams
        ctypes.POINTER(ctypes.c_longlong),  # pos[]
        ctypes.POINTER(ctypes.c_longlong),  # counts[]
        ctypes.POINTER(ctypes.c_longlong),  # bws[]
        ctypes.c_longlong,                  # total
        ctypes.POINTER(ctypes.c_int32), ctypes.c_longlong,  # plan, pad
        ctypes.POINTER(ctypes.c_longlong),  # rows_needed out
    ]
    lib.pftpu_delta_parse_plan.restype = ctypes.c_ssize_t
    lib.pftpu_delta_parse_plan.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # data
        ctypes.c_int, ctypes.c_int,         # value_bytes, allow_wide
        ctypes.POINTER(ctypes.c_longlong),  # mb_byte[]
        ctypes.POINTER(ctypes.c_longlong),  # mb_bw[]
        ctypes.POINTER(ctypes.c_longlong),  # mb_min[]
        ctypes.c_size_t,                    # cap_rows
        ctypes.POINTER(ctypes.c_longlong),  # out_scalars[5]
    ]
    lib.pftpu_lz4_decompress.restype = ctypes.c_ssize_t
    lib.pftpu_lz4_decompress.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
    ]
    lib.pftpu_rle_count_equal.restype = ctypes.c_ssize_t
    lib.pftpu_rle_count_equal.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # data
        ctypes.c_longlong, ctypes.c_int,    # num_values, bit_width
        ctypes.c_longlong,                  # target
        ctypes.POINTER(ctypes.c_longlong),  # count out
    ]
    lib.pftpu_split_pages.restype = ctypes.c_ssize_t
    lib.pftpu_split_pages.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # data
        ctypes.c_longlong,                  # num_values
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_size_t,  # out, cap pages
    ]
    lib.pftpu_dedup_bytes.restype = ctypes.c_ssize_t
    lib.pftpu_dedup_bytes.argtypes = [
        ctypes.c_void_p, ctypes.c_size_t,   # offsets, n
        ctypes.c_void_p,                    # pool
        ctypes.c_void_p, ctypes.c_void_p,   # indices out, uniq_ids out
    ]
    return lib


def available() -> bool:
    return _load() is not None


def snappy_compress(data: bytes) -> bytes:
    lib = _load()
    cap = lib.pftpu_snappy_max_compressed_size(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.pftpu_snappy_compress(data, len(data), out, cap)
    if n < 0:
        raise ValueError("native snappy compression failed")
    return out.raw[:n]


def snappy_decompress(data: bytes, uncompressed_size: Optional[int] = None) -> bytes:
    lib = _load()
    if uncompressed_size is None:
        uncompressed_size = lib.pftpu_snappy_uncompressed_size(data, len(data))
        if uncompressed_size < 0:
            raise ValueError("native snappy: bad stream header")
    # the size is a 64-bit varint PARSED OFF THE WIRE (or a caller-held
    # header field): cap it to the format's i32 range before it becomes
    # a buffer — the audit's one real gap at the ctypes boundary
    usize = checked_alloc_size(uncompressed_size, "snappy uncompressed")
    out = ctypes.create_string_buffer(max(usize, 1))
    n = lib.pftpu_snappy_decompress(data, len(data), out, usize)
    if n < 0:
        raise ValueError("native snappy decompression failed")
    return out.raw[:n]


def snappy_decompress_into(data, out_arr, offset: int, out_size: int) -> None:
    """Decompress directly into ``out_arr[offset:offset+out_size]`` (a
    C-contiguous uint8 ndarray) — the zero-extra-copy arena staging path."""
    lib = _load()
    ptr = ctypes.c_char_p(out_arr.ctypes.data + offset)
    n = lib.pftpu_snappy_decompress(data, len(data), ptr, out_size)
    if n < 0:
        raise ValueError("native snappy decompression failed")
    if n != out_size:
        raise ValueError(f"snappy decoded {n} bytes, expected {out_size}")


def zstd_decompress_into(data, out_arr, offset: int, out_size: int) -> None:
    """RFC 8878 decode directly into ``out_arr[offset:offset+out_size]``."""
    lib = _load()
    ptr = ctypes.c_char_p(out_arr.ctypes.data + offset)
    n = lib.pftpu_zstd_decompress(data, len(data), ptr, out_size)
    if n == -2:
        raise ValueError("native zstd: output exceeds the declared size")
    if n < 0:
        raise ValueError("native zstd: malformed frame")
    if n != out_size:
        raise ValueError(f"native zstd: decoded {n} bytes, expected {out_size}")


def zstd_decompress(data: bytes, uncompressed_size: int) -> bytes:
    """First-party RFC 8878 decoder (see src/pftpu_zstd.cc)."""
    lib = _load()
    usize = checked_alloc_size(uncompressed_size, "zstd uncompressed")
    out = ctypes.create_string_buffer(max(usize, 1))
    n = lib.pftpu_zstd_decompress(data, len(data), out, usize)
    if n == -2:
        raise ValueError("native zstd: output exceeds the declared size")
    if n < 0:
        raise ValueError("native zstd: malformed frame")
    if n != uncompressed_size:
        raise ValueError(
            f"native zstd: decoded {n} bytes, expected {uncompressed_size}"
        )
    return out.raw[:n]


def zstd_decompress_unsized(data: bytes, cap: int) -> bytes:
    """Decode without a known output size into a ``cap``-byte buffer; raises
    ``ValueError('... grow ...')`` when the buffer is too small."""
    lib = _load()
    # clamp to the i32 ceiling BEFORE blessing: the grow loop above this
    # face doubles past 2**31 as its own exit condition, and the last
    # probe must still run (at the ceiling) rather than raise corruption
    bcap = checked_alloc_size(min(cap, (1 << 31) - 1), "zstd grow cap")
    out = ctypes.create_string_buffer(max(bcap, 1))
    n = lib.pftpu_zstd_decompress(data, len(data), out, bcap)
    if n == -2:
        raise ValueError("native zstd: output buffer too small, grow and retry")
    if n < 0:
        raise ValueError("native zstd: malformed frame")
    return out.raw[:n]


def zstd_compress(data: bytes) -> bytes:
    """Store-mode zstd frames (raw blocks): spec-compliant, uncompressed."""
    lib = _load()
    cap = lib.pftpu_zstd_max_compressed_size(len(data))
    out = ctypes.create_string_buffer(cap)
    n = lib.pftpu_zstd_compress_store(data, len(data), out, cap)
    if n < 0:
        raise ValueError("native zstd: store encode failed")
    return out.raw[:n]


def plain_ba_scan(data, max_values: int):
    """Walk a PLAIN BYTE_ARRAY length chain natively (zero-copy input).

    Returns (starts, lengths) int64 arrays of the values found (may be
    fewer than max_values when the buffer ends first).
    """
    import numpy as np

    lib = _load()
    nv = checked_alloc_size(max_values, "PLAIN BYTE_ARRAY value count")
    starts = np.empty(nv, dtype=np.int64)
    lengths = np.empty(nv, dtype=np.int64)
    arr = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = lib.pftpu_plain_ba_scan(
        ctypes.c_char_p(arr.ctypes.data), len(arr), max_values,
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
    )
    if n < 0:
        raise ValueError("malformed PLAIN BYTE_ARRAY stream")
    return starts[:n], lengths[:n]


def lz4_decompress_capped(data: bytes, max_size: int) -> bytes:
    """Decode one LZ4 raw block natively; output may be any size ≤ cap
    (Hadoop-framed records hold codec-buffer-sized inner blocks whose
    exact decoded length is unknown until decoded)."""
    lib = _load()
    cap = checked_alloc_size(max_size, "LZ4 output cap")
    out = ctypes.create_string_buffer(cap)
    n = lib.pftpu_lz4_decompress(data, len(data), out, cap)
    if n == -2:
        raise ValueError("LZ4 output larger than cap")
    if n < 0:
        raise ValueError("malformed LZ4 block")
    return out.raw[:n]


def lz4_decompress(data: bytes, uncompressed_size: int) -> bytes:
    """Decode one LZ4 raw block natively (exact output size required)."""
    lib = _load()
    usize = checked_alloc_size(uncompressed_size, "LZ4 uncompressed")
    out = ctypes.create_string_buffer(usize)
    n = lib.pftpu_lz4_decompress(data, len(data), out, usize)
    if n == -2:
        raise ValueError("LZ4 output larger than expected size")
    if n < 0:
        raise ValueError("malformed LZ4 block")
    if n != uncompressed_size:
        raise ValueError(
            f"LZ4 block decoded {n} bytes, expected {uncompressed_size}"
        )
    return out.raw[:n]


def split_pages(data, num_values: int):
    """Scan a column chunk's Thrift page-header chain natively.

    Returns an int64 ndarray of shape (n_pages, 16); see
    pftpu_split_pages in pftpu_native.cc for the slot layout."""
    import numpy as np

    lib = _load()
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    cap = 64
    while True:
        out = np.empty((cap, 16), dtype=np.int64)
        n = lib.pftpu_split_pages(
            arr.ctypes.data, len(arr), num_values,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), cap,
        )
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            raise ValueError("malformed page header chain")
        return out[:n]


def rle_count_equal(data, num_values: int, bit_width: int, target: int,
                    pos: int = 0) -> Optional[int]:
    """Count decoded values == target in an RLE/bit-packed hybrid stream
    without expanding it (native).  Returns None when the lib is absent."""
    import numpy as np

    lib = _load()
    if lib is None:
        return None
    if bit_width > 57:
        # the native rolling 64-bit window needs (bitpos&7)+bit_width ≤ 64;
        # wider fields fall back to the exact Python path
        return None
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    if pos < 0 or pos > len(arr):
        raise ValueError(f"parse position {pos} outside buffer of {len(arr)} bytes")
    out = ctypes.c_longlong(0)
    rc = lib.pftpu_rle_count_equal(
        arr.ctypes.data + pos, len(arr) - pos, num_values, bit_width,
        target, ctypes.byref(out),
    )
    if rc < 0:
        raise ValueError("native RLE count failed (malformed stream)")
    return out.value


def rle_parse_runs(data: bytes, num_values: int, bit_width: int, pos: int = 0):
    """Parse an RLE/bit-packed hybrid run table natively.

    Returns (run_table int64 ndarray (n,4), end_pos) matching
    ``format.encodings.rle_hybrid.parse_runs``.
    """
    import numpy as np

    lib = _load()
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    if pos < 0 or pos > len(arr):
        raise ValueError(f"parse position {pos} outside buffer of {len(arr)} bytes")
    base_ptr = arr.ctypes.data + pos
    avail = len(arr) - pos
    # worst case one run per value; the count is a parsed page-header
    # field, so it flows through the i32 cap before sizing the table
    cap = max(16, checked_alloc_size(num_values, "RLE run table rows"))
    while True:
        table = np.empty((cap, 4), dtype=np.int64)
        end = ctypes.c_longlong(0)
        n = lib.pftpu_rle_parse_runs(
            base_ptr, avail, num_values, bit_width,
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)), cap,
            ctypes.byref(end),
        )
        if n == -2:  # capacity exceeded
            cap *= 2
            continue
        if n < 0:
            raise ValueError("native RLE parse failed")
        table = table[:n]
        if pos:
            table[table[:, 0] == 1, 2] += pos
        return table, end.value + pos


def rle_parse_runs_batch(data, pos, counts, bws):
    """Parse many independent RLE/bit-packed streams of one buffer in ONE
    native call (the staging loop parses one stream per page; per-call
    ctypes overhead dominated the actual parse work).

    Returns ``(table, runs_per_stream)``: a concatenated int64 run table
    of shape (n, 4) with byte offsets absolute in ``data``, and the run
    count of each stream (``np.split`` boundaries via cumsum).
    """
    import numpy as np

    lib = _load()
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    bws = np.ascontiguousarray(bws, dtype=np.int64)
    ns = len(pos)
    if len(counts) != ns or len(bws) != ns:
        raise ValueError("pos/counts/bws length mismatch")
    runs = np.zeros(ns, dtype=np.int64)
    ll = ctypes.POINTER(ctypes.c_longlong)
    cap = max(64, checked_alloc_size(
        int(counts.sum()) // 4 + 2 * ns, "RLE batch run table rows"
    ))
    while True:
        table = np.empty((cap, 4), dtype=np.int64)
        n = lib.pftpu_rle_parse_runs_batch(
            arr.ctypes.data, len(arr), ns,
            pos.ctypes.data_as(ll), counts.ctypes.data_as(ll),
            bws.ctypes.data_as(ll),
            table.ctypes.data_as(ll), cap, runs.ctypes.data_as(ll),
        )
        if n == -2:  # capacity exceeded
            cap *= 2
            continue
        if n < 0:
            raise ValueError("native RLE batch parse failed")
        return table[:n], runs


class PlanOverflowNative(ValueError):
    """Native plan build hit an int32 limit (byte offset or run length);
    translated by callers into bitops.PlanOverflow."""


class PlanPadExceeded(ValueError):
    """The plan needs more rows than ``pad_runs``; ``needed`` carries the
    exact count so the caller can re-size in a single retry."""

    def __init__(self, needed: int, pad_runs: int):
        super().__init__(f"run tables ({needed}) exceed padding ({pad_runs})")
        self.needed = needed


def rle_plan5_batch(data, pos, counts, bws, total: int, pad_runs: int):
    """Build the flat 5×pad int32 device plan for many streams in one
    native pass.  Returns (plan int32[5*pad], rows_used)."""
    import numpy as np

    lib = _load()
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    pos = np.ascontiguousarray(pos, dtype=np.int64)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    bws = np.ascontiguousarray(bws, dtype=np.int64)
    ll = ctypes.POINTER(ctypes.c_longlong)
    pad = checked_alloc_size(pad_runs, "RLE plan pad rows")
    plan = np.empty(5 * pad, dtype=np.int32)
    needed = ctypes.c_longlong(0)
    n = lib.pftpu_rle_plan5_batch(
        arr.ctypes.data, len(arr), len(pos),
        pos.ctypes.data_as(ll), counts.ctypes.data_as(ll),
        bws.ctypes.data_as(ll), total,
        plan.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), pad_runs,
        ctypes.byref(needed),
    )
    if n == -4:
        raise PlanOverflowNative("int32 plan overflow")
    if n == -2:
        raise PlanPadExceeded(int(needed.value), pad_runs)
    if n == -3:
        raise ValueError(f"run counts do not sum to {total}")
    if n < 0:
        raise ValueError("native plan build failed (malformed stream)")
    return plan, int(n)


def delta_parse_plan(data, value_bytes: int, allow_wide: bool):
    """Native DELTA_BINARY_PACKED plan parse (tpu/engine.py twin).

    Returns the plan dict, or None for malformed/unsupported streams
    (the caller's host-fallback signal)."""
    import numpy as np

    lib = _load()
    if isinstance(data, np.ndarray):
        arr = data if (data.dtype == np.uint8 and data.flags.c_contiguous) else (
            np.ascontiguousarray(data).view(np.uint8)
        )
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    ll = ctypes.POINTER(ctypes.c_longlong)
    cap = 4096
    while True:
        mb_byte = np.empty(cap, np.int64)
        mb_bw = np.empty(cap, np.int64)
        mb_min = np.empty(cap, np.int64)
        scalars = np.zeros(5, np.int64)
        n = lib.pftpu_delta_parse_plan(
            arr.ctypes.data, len(arr), value_bytes, int(allow_wide),
            mb_byte.ctypes.data_as(ll), mb_bw.ctypes.data_as(ll),
            mb_min.ctypes.data_as(ll), cap, scalars.ctypes.data_as(ll),
        )
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            return None
        k = max(int(n), 1)
        if n == 0:
            mb_byte[0] = mb_bw[0] = mb_min[0] = 0
        return {
            "mb_bytebase": mb_byte[:k].copy(),
            "mb_bw": mb_bw[:k].copy(),
            "mb_min_delta": mb_min[:k].copy(),
            "first_value": int(scalars[0]),
            "values_per_miniblock": int(scalars[1]),
            "total": int(scalars[2]),
            "end_pos": int(scalars[3]),
            "wide": bool(scalars[4]),
        }


def dedup_bytes(offsets, pool):
    """First-appearance dedup of byte slices (the writer's dictionary
    build): ``offsets`` int64[n+1] delimits value i in the uint8
    ``pool``.  Returns ``(indices uint32[n], uniq_ids int64[k])`` —
    per-value first-appearance rank and the value index of each
    distinct slice in first-appearance order.  O(n) hash table in C vs
    the NumPy fallback's padded-key sort."""
    import numpy as np

    lib = _load()
    n = len(offsets) - 1
    indices = np.empty(n, dtype=np.uint32)
    uniq_ids = np.empty(max(n, 1), dtype=np.int64)
    off = np.ascontiguousarray(offsets, dtype=np.int64)
    pl = np.ascontiguousarray(pool, dtype=np.uint8)
    k = lib.pftpu_dedup_bytes(
        off.ctypes.data, n, pl.ctypes.data,
        indices.ctypes.data, uniq_ids.ctypes.data,
    )
    if k < 0:
        raise MemoryError("native dedup_bytes: allocation failed")
    return indices, uniq_ids[:k].copy()
