"""Sharded decode over a device mesh — the first-class parallel component
the reference explicitly declines to provide (``trySplit()`` → null,
``ParquetReader.java:214-217``; SURVEY.md §2.4 item 3 names this a new
component with no reference counterpart).

Three parallel axes, composable over one `jax.sharding.Mesh`:

  * **"rg" (data parallel)** — row groups are independent by construction;
    each device decodes its shard of row groups.
  * **"seq" (sequence parallel)** — within a chunk, the run-table expansion
    is an arbitrary-offset computation (`rle_expand` binary-searches each
    output element independently), so the *output index space* shards
    cleanly: each device expands a contiguous slice of the column.
  * **"dict" (tensor parallel)** — the dictionary shards across devices;
    each device gathers the indices that land in its shard and a `psum`
    over the axis assembles full values (a masked-gather + reduce, the
    classic TP embedding-lookup pattern).

Multi-host: the same meshes span hosts via jax's global device set; row
groups naturally shard across hosts over DCN (each host reads only its
groups' byte ranges), while "seq"/"dict" collectives ride ICI inside a pod.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..tpu import bitops


def make_mesh(
    n_devices: Optional[int] = None, rg: Optional[int] = None,
    seq: int = 1, dict_: int = 1,
) -> Mesh:
    """Build a (rg, seq, dict) mesh over the first ``n_devices`` devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if rg is None:
        rg = n // (seq * dict_)
    if rg * seq * dict_ != n:
        raise ValueError(f"mesh {rg}x{seq}x{dict_} != {n} devices")
    arr = np.array(devices[:n]).reshape(rg, seq, dict_)
    return Mesh(arr, ("rg", "seq", "dict"))


# ---------------------------------------------------------------------------
# The sharded decode step
# ---------------------------------------------------------------------------

def _expand_slice(buf, out_end, kind, value, bitbase, out_offset, per, bw):
    """Expand ``per`` outputs of a run table starting at ``out_offset``
    (the sequence-parallel unit: any output slice computes independently)."""
    out_idx = jax.lax.broadcasted_iota(jnp.int32, (per, 1), 0).reshape(per) + out_offset
    rid = jnp.searchsorted(out_end, out_idx, side="right").astype(jnp.int32)
    rid = jnp.minimum(rid, out_end.shape[0] - 1)
    run_start = jnp.where(rid == 0, 0, out_end[jnp.maximum(rid - 1, 0)])
    within = out_idx - run_start
    bitpos = bitbase[rid] + within * bw
    packed = bitops.extract_bits(buf, bitpos, bw).astype(jnp.int32)
    return jnp.where(kind[rid] == 0, value[rid], packed)


def build_sharded_decode_step(mesh: Mesh, n_per_group: int, bw: int, dict_pad: int,
                              dtype=jnp.float32):
    """Compile a full sharded decode step over ``mesh``.

    Inputs (global shapes):
      * ``bufs``      (G, B) uint8   — per-row-group value streams, sharded over "rg"
      * run tables    (G, R) int32   — sharded over "rg", replicated over "seq"/"dict"
      * ``dictionary`` (dict_pad,)   — sharded over "dict" (tensor parallel)

    Output: (G, n_per_group) decoded values, sharded over ("rg", "seq").

    Each device expands its output slice of its row groups, gathers from its
    dictionary shard, and a psum over "dict" assembles full values — dp, sp,
    and tp composed in one jitted step.
    """
    seq_size = mesh.shape["seq"]
    dict_size = mesh.shape["dict"]
    if n_per_group % seq_size:
        raise ValueError("n_per_group must divide evenly over the seq axis")
    if dict_pad % dict_size:
        raise ValueError("dict_pad must divide evenly over the dict axis")
    per = n_per_group // seq_size
    dict_shard = dict_pad // dict_size

    def step(bufs, out_end, kind, value, bitbase, dictionary):
        # local shapes: bufs (g, B); tables (g, R); dictionary (dict_shard,)
        seq_i = jax.lax.axis_index("seq")
        dict_i = jax.lax.axis_index("dict")
        out_offset = seq_i * per

        def one_group(buf, oe, kd, vl, bb):
            idx = _expand_slice(buf, oe, kd, vl, bb, out_offset, per, bw)
            # tensor-parallel gather: mask indices outside my dictionary
            # shard, gather locally, psum assembles the full values
            local = idx - dict_i * dict_shard
            in_shard = (local >= 0) & (local < dict_shard)
            safe = jnp.clip(local, 0, dict_shard - 1)
            vals = jnp.take(dictionary, safe, axis=0)
            return jnp.where(in_shard, vals, jnp.zeros((), dtype=dictionary.dtype))

        partial_vals = jax.vmap(one_group)(bufs, out_end, kind, value, bitbase)
        return jax.lax.psum(partial_vals, axis_name="dict")

    spec_rg = P("rg", None)
    return jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(spec_rg, spec_rg, spec_rg, spec_rg, spec_rg, P("dict")),
            out_specs=P("rg", "seq"),
        )
    )


# ---------------------------------------------------------------------------
# Sharded file reading (data-parallel row groups)
# ---------------------------------------------------------------------------

class ShardedColumn:
    """A globally-sharded decoded column: dense values + optional null mask."""

    __slots__ = ("values", "mask")

    def __init__(self, values: jax.Array, mask: Optional[jax.Array]):
        self.values = values
        self.mask = mask

    def __repr__(self):
        return f"ShardedColumn({self.values.shape}, nullable={self.mask is not None})"


def _assemble_global(parts, devices, mesh, axis):
    """Blocked assembly: group i of n_groups goes to device i*n_dev//n_groups;
    contiguous groups concatenate per device so the global array is sharded
    over the mesh axis (requires n_groups % n_dev == 0)."""
    n_dev = len(devices)
    per_dev = len(parts) // n_dev
    shards = []
    for d in range(n_dev):
        chunk = parts[d * per_dev : (d + 1) * per_dev]
        local = chunk[0] if len(chunk) == 1 else jnp.concatenate(chunk)
        shards.append(jax.device_put(local, devices[d]))
    global_shape = (sum(p.shape[0] for p in parts),) + parts[0].shape[1:]
    return jax.make_array_from_single_device_arrays(
        global_shape, NamedSharding(mesh, P(axis)), shards
    )


def read_table_sharded(
    source,
    mesh: Mesh,
    columns: Optional[Sequence[str]] = None,
    axis: str = "rg",
) -> Dict[str, ShardedColumn]:
    """Decode a parquet file with row groups data-parallel over ``mesh``.

    Each mesh slot along ``axis`` decodes a contiguous block of row groups
    (device-placed jits), and per-group arrays assemble into one global
    array per column via ``jax.make_array_from_single_device_arrays`` —
    rows end up sharded over the mesh axis, ready for sharded compute
    without reshuffling.

    Requirements (violations raise, never silently degrade): uniform row
    counts per group, group count divisible by the axis device count.
    String columns are not yet assembled globally.
    """
    from ..tpu.engine import TpuRowGroupReader

    devices = mesh.devices.reshape(-1)
    n_dev = len(devices)
    readers = {d: TpuRowGroupReader(source, device=d) for d in set(devices)}
    try:
        any_reader = next(iter(readers.values()))
        n_groups = any_reader.num_row_groups
        if n_groups % n_dev:
            raise ValueError(
                f"{n_groups} row groups do not shard evenly over {n_dev} "
                f"devices; re-chunk the file or use a smaller mesh axis"
            )
        per_group: Optional[int] = None
        vals: Dict[str, list] = {}
        masks: Dict[str, list] = {}
        per_dev = n_groups // n_dev
        for gi in range(n_groups):
            dev = devices[gi // per_dev]
            cols = readers[dev].read_row_group(gi, columns)
            for name, dc in cols.items():
                if dc.is_strings:
                    raise NotImplementedError(
                        "sharded string assembly lands with the string kernel"
                    )
                if dc.is_repeated:
                    # repeated columns yield a non-row-aligned value stream
                    # + levels; global list assembly is not implemented —
                    # decode per group and DeviceColumn.assemble() instead
                    raise NotImplementedError(
                        "sharded assembly of repeated (nested) columns is "
                        "not supported; use TpuRowGroupReader per group"
                    )
                rows = dc.values.shape[0]
                if per_group is None:
                    per_group = rows
                elif rows != per_group:
                    raise ValueError(
                        f"row group {gi} has {rows} rows != {per_group}; "
                        "uniform groups required for global assembly"
                    )
                vals.setdefault(name, []).append(dc.values)
                masks.setdefault(name, []).append(dc.mask)
        out: Dict[str, ShardedColumn] = {}
        for name, parts in vals.items():
            gv = _assemble_global(parts, devices, mesh, axis)
            mparts = masks[name]
            if any(m is not None for m in mparts):
                mparts = [
                    m if m is not None else jnp.zeros(per_group, jnp.bool_)
                    for m in mparts
                ]
                gm = _assemble_global(mparts, devices, mesh, axis)
            else:
                gm = None
            out[name] = ShardedColumn(gv, gm)
        return out
    finally:
        for r in readers.values():
            r.close()
