"""Sharded decode over a device mesh — the first-class parallel component
the reference explicitly declines to provide (``trySplit()`` → null,
``ParquetReader.java:214-217``; SURVEY.md §2.4 item 3 names this a new
component with no reference counterpart).

Three parallel axes, composable over one `jax.sharding.Mesh`:

  * **"rg" (data parallel)** — row groups are independent by construction;
    each device decodes its shard of row groups.
  * **"seq" (sequence parallel)** — within a chunk, the run-table expansion
    is an arbitrary-offset computation (`rle_expand` binary-searches each
    output element independently), so the *output index space* shards
    cleanly: each device expands a contiguous slice of the column.
  * **"dict" (tensor parallel)** — the dictionary shards across devices;
    each device gathers the indices that land in its shard and a `psum`
    over the axis assembles full values (a masked-gather + reduce, the
    classic TP embedding-lookup pattern).

Multi-host: the same meshes span hosts via jax's global device set; row
groups naturally shard across hosts over DCN (each host reads only its
groups' byte ranges), while "seq"/"dict" collectives ride ICI inside a pod.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..tpu import bitops


def make_mesh(
    n_devices: Optional[int] = None, rg: Optional[int] = None,
    seq: int = 1, dict_: int = 1,
) -> Mesh:
    """Build a (rg, seq, dict) mesh over the first ``n_devices`` devices."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if rg is None:
        rg = n // (seq * dict_)
    if rg * seq * dict_ != n:
        raise ValueError(f"mesh {rg}x{seq}x{dict_} != {n} devices")
    arr = np.array(devices[:n]).reshape(rg, seq, dict_)
    return Mesh(arr, ("rg", "seq", "dict"))


# ---------------------------------------------------------------------------
# The sharded decode step
# ---------------------------------------------------------------------------

def _expand_slice(buf, out_end, kind, value, bytebase, out_offset, per, bw):
    """Expand ``per`` outputs of a run table starting at ``out_offset``
    (the sequence-parallel unit: any output slice computes independently)."""
    out_idx = jax.lax.broadcasted_iota(jnp.int32, (per, 1), 0).reshape(per) + out_offset
    rid = jnp.searchsorted(out_end, out_idx, side="right").astype(jnp.int32)
    rid = jnp.minimum(rid, out_end.shape[0] - 1)
    run_start = jnp.where(rid == 0, 0, out_end[jnp.maximum(rid - 1, 0)])
    within = out_idx - run_start
    packed = bitops.extract_bits_at(
        buf, bytebase[rid], within * bw, bw
    ).astype(jnp.int32)
    return jnp.where(kind[rid] == 0, value[rid], packed)


def build_sharded_decode_step(mesh: Mesh, n_per_group: int, bw: int, dict_pad: int,
                              dtype=jnp.float32):
    """Compile a full sharded decode step over ``mesh``.

    Inputs (global shapes):
      * ``bufs``      (G, B) uint8   — per-row-group value streams, sharded over "rg"
      * run tables    (G, R) int32   — sharded over "rg", replicated over "seq"/"dict"
      * ``dictionary`` (dict_pad,)   — sharded over "dict" (tensor parallel)

    Output: (G, n_per_group) decoded values, sharded over ("rg", "seq").

    Each device expands its output slice of its row groups, gathers from its
    dictionary shard, and a psum over "dict" assembles full values — dp, sp,
    and tp composed in one jitted step.
    """
    seq_size = mesh.shape["seq"]
    dict_size = mesh.shape["dict"]
    if n_per_group % seq_size:
        raise ValueError("n_per_group must divide evenly over the seq axis")
    if dict_pad % dict_size:
        raise ValueError("dict_pad must divide evenly over the dict axis")
    per = n_per_group // seq_size
    dict_shard = dict_pad // dict_size

    def step(bufs, out_end, kind, value, bitbase, dictionary):
        # local shapes: bufs (g, B); tables (g, R); dictionary (dict_shard,)
        seq_i = jax.lax.axis_index("seq")
        dict_i = jax.lax.axis_index("dict")
        out_offset = seq_i * per

        def one_group(buf, oe, kd, vl, bb):
            idx = _expand_slice(buf, oe, kd, vl, bb, out_offset, per, bw)
            # tensor-parallel gather: mask indices outside my dictionary
            # shard, gather locally, psum assembles the full values
            local = idx - dict_i * dict_shard
            in_shard = (local >= 0) & (local < dict_shard)
            safe = jnp.clip(local, 0, dict_shard - 1)
            vals = jnp.take(dictionary, safe, axis=0)
            return jnp.where(in_shard, vals, jnp.zeros((), dtype=dictionary.dtype))

        partial_vals = jax.vmap(one_group)(bufs, out_end, kind, value, bitbase)
        return jax.lax.psum(partial_vals, axis_name="dict")

    spec_rg = P("rg", None)
    return jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(spec_rg, spec_rg, spec_rg, spec_rg, spec_rg, P("dict")),
            out_specs=P("rg", "seq"),
        )
    )


# ---------------------------------------------------------------------------
# Sharded file reading (data-parallel row groups)
# ---------------------------------------------------------------------------

class ShardedColumn:
    """A globally-sharded decoded column.

    ``values``: dense rows sharded over the mesh axis.  For strings the
    shape is ``(N, W)`` uint8 (right-padded bytes) with per-row byte
    ``lengths``.  When the file is ragged (non-uniform row groups or a
    group count that does not divide the device count) rows are laid out
    on a fixed per-group stride and ``row_mask`` marks the real rows
    (True = valid); ``num_rows`` is always the true total.  Uniform,
    evenly-divisible files keep the exact flat layout (``row_mask`` None).
    """

    __slots__ = ("values", "mask", "lengths", "row_mask", "num_rows")

    def __init__(self, values, mask, lengths=None, row_mask=None, num_rows=None):
        self.values = values
        self.mask = mask
        self.lengths = lengths
        self.row_mask = row_mask
        self.num_rows = values.shape[0] if num_rows is None else num_rows

    def __repr__(self):
        return (
            f"ShardedColumn({self.values.shape}, rows={self.num_rows}, "
            f"nullable={self.mask is not None}, strings={self.lengths is not None})"
        )

    def to_list(self):
        """Host materialization (tests/debugging): list of python values."""
        vals = np.asarray(self.values)
        mask = None if self.mask is None else np.asarray(self.mask)
        valid = (
            np.ones(vals.shape[0], bool)
            if self.row_mask is None
            else np.asarray(self.row_mask)
        )
        out = []
        if self.lengths is not None:
            lens = np.asarray(self.lengths)
            for i in np.flatnonzero(valid):
                if mask is not None and mask[i]:
                    out.append(None)
                else:
                    out.append(vals[i, : lens[i]].tobytes())
        else:
            for i in np.flatnonzero(valid):
                out.append(None if mask is not None and mask[i] else vals[i].item())
        return out


class ShardedNestedColumn:
    """A repeated (nested) column sharded at the row-group grain.

    TPUs want rectangles, and a repeated column's value stream is not
    row-aligned — so the global layout keeps one padded slot per row
    group, sharded over the mesh axis on the leading (group) axis:

      * ``def_levels``/``rep_levels``: ``(G, L)`` int32, padded per group
      * ``values``: ``(G, V)`` dense non-null values (``(G, V, W)`` uint8
        for strings, with ``lengths`` ``(G, V)``)
      * ``level_counts``: ``(G,)`` true level count per group
      * ``group_rows``: ``(G,)`` true row count per group (0 = pad group)

    Device compute can map over the group axis; host record assembly
    (Dremel) is :meth:`to_pylist`.
    """

    __slots__ = (
        "descriptor", "values", "lengths", "def_levels", "rep_levels",
        "level_counts", "group_rows",
    )

    def __init__(self, descriptor, values, lengths, def_levels, rep_levels,
                 level_counts, group_rows):
        self.descriptor = descriptor
        self.values = values
        self.lengths = lengths
        self.def_levels = def_levels
        self.rep_levels = rep_levels
        self.level_counts = level_counts
        self.group_rows = group_rows

    def __repr__(self):
        return (
            f"ShardedNestedColumn({'.'.join(self.descriptor.path)}, "
            f"groups={self.def_levels.shape[0]}, values={self.values.shape})"
        )

    def to_pylist(self, schema):
        """Assemble every group's records on host (Dremel), in file order."""
        from ..batch.columns import ByteArrayColumn, ColumnBatch
        from ..batch.nested import assemble_nested

        defs_all = np.asarray(self.def_levels)
        reps_all = np.asarray(self.rep_levels)
        counts = np.asarray(self.level_counts)
        rows = np.asarray(self.group_rows)
        vals_all = np.asarray(self.values)
        lens_all = None if self.lengths is None else np.asarray(self.lengths)
        max_def = self.descriptor.max_definition_level
        out = []
        for g in range(defs_all.shape[0]):
            if rows[g] == 0:
                continue
            ln = int(counts[g])
            defs = defs_all[g, :ln].astype(np.uint32)
            reps = reps_all[g, :ln].astype(np.uint32)
            nn = int(np.count_nonzero(defs == max_def))
            if lens_all is not None:
                lens = lens_all[g, :nn].astype(np.int64)
                offsets = np.zeros(nn + 1, dtype=np.int64)
                np.cumsum(lens, out=offsets[1:])
                rowsv = vals_all[g, :nn]
                if nn:
                    flat = rowsv[np.arange(rowsv.shape[1])[None, :] < lens[:, None]]
                else:
                    flat = np.zeros(0, np.uint8)
                vals = ByteArrayColumn(offsets, flat)
            else:
                vals = vals_all[g, :nn]
            batch = ColumnBatch(self.descriptor, ln, vals, defs, reps)
            out.extend(assemble_nested(schema, batch).to_pylist())
        return out


def _pad_rows(arr, rows: int, cols: Optional[int] = None, xp=jnp):
    """Zero-pad ``arr`` to ``rows`` on axis 0 (and ``cols`` on axis 1).

    ``xp`` picks the array library (jnp here; multihost passes np for its
    host-side staging) so both shard layers share one pad rule."""
    widths = [(0, rows - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    if cols is not None:
        widths[1] = (0, cols - arr.shape[1])
    if all(w == (0, 0) for w in widths):
        return arr
    return xp.pad(arr, widths)


def _assemble_blocks(local_per_device, devices, mesh, axis):
    """Stitch per-device local arrays (uniform shapes) into one global
    array sharded over ``mesh[axis]``."""
    shards = [
        jax.device_put(local, d) for local, d in zip(local_per_device, devices)
    ]
    global_shape = (
        sum(s.shape[0] for s in shards),
    ) + tuple(shards[0].shape[1:])
    return jax.make_array_from_single_device_arrays(
        global_shape, NamedSharding(mesh, P(axis)), shards
    )


def read_table_sharded(
    source,
    mesh: Mesh,
    columns: Optional[Sequence[str]] = None,
    axis: str = "rg",
) -> Dict[str, Union["ShardedColumn", "ShardedNestedColumn"]]:
    """Decode a parquet file with row groups data-parallel over ``mesh``.

    Each mesh slot along ``axis`` decodes a contiguous block of row groups
    (device-placed jits), and per-group arrays assemble into one global
    array per column via ``jax.make_array_from_single_device_arrays`` —
    rows end up sharded over the mesh axis, ready for sharded compute
    without reshuffling.

    Handles every column kind and file shape:

      * fixed-width columns → flat ``ShardedColumn`` (identical to the
        host row order);
      * strings → padded ``(N, W)`` bytes + ``lengths``;
      * repeated (nested) columns → :class:`ShardedNestedColumn`, sharded
        at the row-group grain;
      * ragged files (non-uniform groups, group count not divisible by
        the device count) → rows on a fixed per-group stride with
        ``row_mask`` marking real rows (jax shards only evenly-divisible
        dims, so raggedness becomes padding + mask, never an error).
    """
    from ..tpu.engine import TpuRowGroupReader

    devices = mesh.devices.reshape(-1)
    n_dev = len(devices)
    readers = {d: TpuRowGroupReader(source, device=d) for d in set(devices)}
    try:
        any_reader = next(iter(readers.values()))
        rgs = any_reader.reader.row_groups
        n_groups = len(rgs)
        rows_per = [int(rg.num_rows or 0) for rg in rgs]
        per_dev = max(1, -(-n_groups // n_dev))
        g_pad = per_dev * n_dev
        stride = max(rows_per) if rows_per else 0
        uniform = g_pad == n_groups and len(set(rows_per)) <= 1

        # decode: group gi belongs to device gi // per_dev
        cols_by_group: List[Dict[str, object]] = []
        for gi in range(n_groups):
            dev = devices[gi // per_dev]
            cols_by_group.append(readers[dev].read_row_group(gi, columns))

        names = list(cols_by_group[0].keys()) if cols_by_group else []
        out: Dict[str, object] = {}
        for name in names:
            parts = [cols_by_group[gi][name] for gi in range(n_groups)]
            if parts[0].is_repeated:
                out[name] = _assemble_nested_sharded(
                    parts, rows_per, devices, per_dev, mesh, axis
                )
            else:
                out[name] = _assemble_flat_sharded(
                    parts, rows_per, devices, per_dev, stride, uniform,
                    mesh, axis,
                )
        return out
    finally:
        for r in readers.values():
            r.close()


def _assemble_flat_sharded(parts, rows_per, devices, per_dev, stride,
                           uniform, mesh, axis):
    """Assemble per-group flat/string DeviceColumns into a ShardedColumn."""
    n_dev = len(devices)
    n_groups = len(parts)
    strings = parts[0].is_strings
    width = max(p.values.shape[1] for p in parts) if strings else None
    any_mask = any(p.mask is not None for p in parts)
    total_rows = sum(rows_per)

    locals_v, locals_m, locals_l, locals_r = [], [], [], []
    for d in range(n_dev):
        vs, ms, ls, rs = [], [], [], []
        for gi in range(d * per_dev, (d + 1) * per_dev):
            if gi < n_groups:
                p, rows = parts[gi], rows_per[gi]
                v = _pad_rows(p.values, stride, width if strings else None)
                m = (
                    _pad_rows(
                        p.mask if p.mask is not None
                        else jnp.zeros(rows, jnp.bool_),
                        stride,
                    )
                    if any_mask
                    else None
                )
                ln = _pad_rows(p.lengths, stride) if strings else None
                valid = jnp.arange(stride) < rows
            else:  # ghost group: padding to make the axis divisible
                shape = (stride, width) if strings else (stride,) + tuple(
                    parts[0].values.shape[1:]
                )
                v = jnp.zeros(shape, parts[0].values.dtype)
                m = jnp.zeros(stride, jnp.bool_) if any_mask else None
                ln = jnp.zeros(stride, parts[0].lengths.dtype) if strings else None
                valid = jnp.zeros(stride, jnp.bool_)
            vs.append(v)
            rs.append(valid)
            if any_mask:
                ms.append(m)
            if strings:
                ls.append(ln)
        locals_v.append(jnp.concatenate(vs))
        locals_r.append(jnp.concatenate(rs))
        if any_mask:
            locals_m.append(jnp.concatenate(ms))
        if strings:
            locals_l.append(jnp.concatenate(ls))

    gv = _assemble_blocks(locals_v, devices, mesh, axis)
    gm = _assemble_blocks(locals_m, devices, mesh, axis) if any_mask else None
    gl = _assemble_blocks(locals_l, devices, mesh, axis) if strings else None
    gr = None if uniform else _assemble_blocks(locals_r, devices, mesh, axis)
    return ShardedColumn(gv, gm, lengths=gl, row_mask=gr, num_rows=total_rows)


def _assemble_nested_sharded(parts, rows_per, devices, per_dev, mesh, axis):
    """Assemble per-group repeated DeviceColumns into a ShardedNestedColumn
    (one padded slot per row group, sharded on the group axis)."""
    n_dev = len(devices)
    n_groups = len(parts)
    strings = parts[0].is_strings
    lmax = max(p.def_levels.shape[0] for p in parts)
    vmax = max(p.values.shape[0] for p in parts)
    width = max(p.values.shape[1] for p in parts) if strings else None

    def per_device(build_one, ghost):
        locals_ = []
        for d in range(n_dev):
            rows = []
            for gi in range(d * per_dev, (d + 1) * per_dev):
                rows.append(build_one(parts[gi]) if gi < n_groups else ghost())
            locals_.append(jnp.stack(rows))
        return locals_

    vdtype = parts[0].values.dtype
    ldtype = parts[0].def_levels.dtype
    gv = _assemble_blocks(
        per_device(
            lambda p: _pad_rows(p.values, vmax, width),
            lambda: jnp.zeros(
                (vmax, width) if strings else (vmax,) + tuple(parts[0].values.shape[1:]),
                vdtype,
            ),
        ),
        devices, mesh, axis,
    )
    gl = (
        _assemble_blocks(
            per_device(
                lambda p: _pad_rows(p.lengths, vmax),
                lambda: jnp.zeros(vmax, parts[0].lengths.dtype),
            ),
            devices, mesh, axis,
        )
        if strings
        else None
    )
    gd = _assemble_blocks(
        per_device(
            lambda p: _pad_rows(p.def_levels, lmax),
            lambda: jnp.zeros(lmax, ldtype),
        ),
        devices, mesh, axis,
    )
    gr = _assemble_blocks(
        per_device(
            lambda p: _pad_rows(p.rep_levels, lmax),
            lambda: jnp.zeros(lmax, ldtype),
        ),
        devices, mesh, axis,
    )
    counts = np.zeros(n_dev * per_dev, np.int32)
    counts[:n_groups] = [p.def_levels.shape[0] for p in parts]
    grow = np.zeros(n_dev * per_dev, np.int32)
    grow[:n_groups] = rows_per
    gcounts = _assemble_blocks(
        [jnp.asarray(counts[d * per_dev : (d + 1) * per_dev]) for d in range(n_dev)],
        devices, mesh, axis,
    )
    ggrow = _assemble_blocks(
        [jnp.asarray(grow[d * per_dev : (d + 1) * per_dev]) for d in range(n_dev)],
        devices, mesh, axis,
    )
    return ShardedNestedColumn(
        parts[0].descriptor, gv, gl, gd, gr, gcounts, ggrow
    )
