"""Multi-host sharded decode over DCN — each process reads only its own
row groups' bytes, and the results assemble into global ``jax.Array``s.

The single-host sibling (``parallel.shard``) shards row groups across the
devices one process owns; this module scales the same axis across
*processes* (hosts): process ``p`` owns the contiguous block of row
groups ``[p·k, (p+1)·k)`` (k = G_pad / process_count — contiguous so
the global array preserves file row order), each host decodes its share
locally (never touching other hosts' byte ranges — the DCN
input-sharding pattern SURVEY.md §5 prescribes), and
``jax.make_array_from_process_local_data`` stitches the per-host shards
into one globally-sharded array without any host ever holding the full
column.

Layout mirrors ``parallel.shard``: ragged files (non-uniform groups,
group counts that don't divide the axis) pad rows onto a fixed per-group
stride with a ``row_mask``; strings are padded ``(N, W)`` bytes +
``lengths``; repeated columns shard at the row-group grain.  Dimensions
that only decode can reveal (string width, non-null value counts) are
agreed across hosts with one tiny ``process_allgather`` — row counts and
level counts come from the footer, which every host reads.

Under a single process (tests, the driver's virtual CPU mesh) this
degrades to a plain sharded decode — same code path, one shard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

from .shard import ShardedNestedColumn, _pad_rows

_pad_np = partial(_pad_rows, xp=np)


def host_shard() -> Tuple[int, int]:
    """This process's ``(host_index, host_count)`` — the value
    ``data.DataLoader(shard=...)`` wants for multihost training.

    The loader shards the dataset's ``(file, row_group)`` unit list into
    contiguous per-host blocks (the same convention
    :func:`read_dataset_sharded` uses for its row-group blocks), so
    loaders built with ``shard=host_shard()`` on every host read
    disjoint units and never overlap.  Per-host loader ``ScanReport``\\ s
    serialize (``as_dict``) and fold into one dataset-level summary with
    ``trace.ScanReport.merge`` — ``trace.scope()`` is contextvar-based
    and never crosses process boundaries, so the merge is explicit.
    """
    return jax.process_index(), jax.process_count()


def _agree_max(matrix: np.ndarray) -> np.ndarray:
    """Global elementwise max of one small per-host integer matrix
    (identity under one process).  A plain read uses exactly one of
    these; a predicate read adds a second for the keep-set union."""
    arr = np.asarray(matrix, np.int64)
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(arr)
    return np.max(gathered, axis=0)


def _dtype_code(dt) -> Tuple[int, int]:
    """Encode a numpy dtype as (kind ordinal, itemsize) integers so it can
    ride the allgather; (0, 0) = this host has no sample (ghost-only)."""
    dt = np.dtype(dt)
    return ord(dt.kind), dt.itemsize


def _dtype_from_code(kind: int, size: int):
    # kind 0 (no host decoded the column) is intercepted by the
    # _schema_meta path before this is ever consulted
    assert kind != 0, "ghost columns resolve dtypes via _schema_meta"
    if chr(kind) == "b":
        return np.bool_
    return np.dtype(f"{chr(kind)}{size}")


def _schema_meta(desc, float64_policy: str):
    """Column shape facts derived from the schema alone — used when no
    host decoded the column (every group pruned or a 0-group file), so
    typed ghost columns still carry the right kind/dtype.  Mirrors the
    engine's output types: (rep, strings, width, vmax, lmax, trail,
    vdtype)."""
    from ..format.parquet_thrift import Type
    from ..tpu.engine import _NP_DTYPE  # the authoritative decode dtypes

    pt = desc.physical_type
    rep = int(desc.max_repetition_level > 0)
    strings = int(pt == Type.BYTE_ARRAY)
    trail = 0
    if pt == Type.BOOLEAN:
        vdtype = np.bool_
    elif pt == Type.DOUBLE:
        # the engine's f64mode applied to its _NP_DTYPE entry
        vdtype = np.float32 if float64_policy == "float32" else (
            np.int64 if float64_policy == "bits" else np.float64
        )
    elif pt in _NP_DTYPE:
        vdtype = np.dtype(_NP_DTYPE[pt])
    elif pt in (Type.FIXED_LEN_BYTE_ARRAY, Type.INT96):
        vdtype = np.uint8
        trail = desc.type_length or (12 if pt == Type.INT96 else 1)
    else:
        vdtype = np.uint8
    # minimum-1 pads keep zero-decoded nested/string shapes well-formed
    return rep, strings, 1, 1, 1, trail, vdtype


@dataclass
class GlobalColumn:
    """A globally-sharded decoded column: dense values + null mask.

    ``row_mask`` (True = real row) appears only for ragged files, where
    rows sit on a fixed per-group stride; ``num_rows`` is the true total.
    Strings carry padded ``(N, W)`` byte matrices plus ``lengths``.
    """

    values: jax.Array
    mask: Optional[jax.Array]  # True where null; None when required
    lengths: Optional[jax.Array] = None
    row_mask: Optional[jax.Array] = None
    num_rows: Optional[int] = None


def read_sharded_global(
    source,
    mesh: Mesh,
    axis: str = "rg",
    columns: Optional[Sequence[str]] = None,
    float64_policy: str = "auto",
    predicate=None,
) -> Dict[str, object]:
    """Decode a parquet file into global arrays sharded over ``mesh[axis]``.

    Each process decodes a contiguous block of row groups, so the
    assembled global array preserves file row order.  All column kinds
    are supported: fixed-width (flat), strings (padded bytes + lengths),
    and repeated columns (:class:`~parquet_floor_tpu.parallel.shard.
    ShardedNestedColumn`, sharded at the row-group grain).  Ragged files
    pad to a per-group stride with a ``row_mask`` instead of raising.

    ``predicate`` (see ``batch.predicate.col``) prunes row groups whose
    statistics/Bloom filters prove no row can match — before any page is
    read or shipped.  Pruned groups stay in the global layout as ghost
    slots (``row_mask`` False), so shardings are identical on every
    process regardless of which groups its predicate dropped.
    """
    return read_dataset_sharded(
        [source], mesh, axis=axis, columns=columns,
        float64_policy=float64_policy, predicate=predicate,
    )


def _check_dataset_schemas(readers) -> None:
    """All files of a dataset must agree on the shared schema contract
    (``format.schema.dataset_schema_key``)."""
    from ..format.schema import dataset_schema_key

    first = dataset_schema_key(readers[0].reader.schema.columns)
    for i, r in enumerate(readers[1:], 1):
        if dataset_schema_key(r.reader.schema.columns) != first:
            raise ValueError(
                f"dataset files disagree on schema: file 0 vs file {i}"
            )


def read_dataset_sharded(
    sources: Sequence,
    mesh: Mesh,
    axis: str = "rg",
    columns: Optional[Sequence[str]] = None,
    float64_policy: str = "auto",
    predicate=None,
) -> Dict[str, object]:
    """:func:`read_sharded_global` over the CONCATENATION of many files'
    row groups — the dataset-directory form.  Global arrays preserve
    (file order, then row-group order); every process reads every
    footer (cheap) but only its own groups' pages.  Schemas must agree
    across files (:func:`_check_dataset_schemas`)."""
    import os
    from contextlib import ExitStack

    from ..tpu.engine import TpuRowGroupReader, iter_dataset_row_groups

    if isinstance(sources, (str, bytes, os.PathLike)):
        raise TypeError(
            "read_dataset_sharded takes a LIST of sources; for a single "
            "file use read_sharded_global (or pass [source])"
        )
    if not sources:
        raise ValueError("read_dataset_sharded needs at least one source")
    n_proc = jax.process_count()
    pid = jax.process_index()
    n_axis = int(mesh.shape[axis])
    sharding = NamedSharding(mesh, P(axis))

    with ExitStack() as stack:
        readers = [
            stack.enter_context(
                TpuRowGroupReader(s, float64_policy=float64_policy)
            )
            for s in sources
        ]
        _check_dataset_schemas(readers)
        reader = readers[0]  # schema/meta authority
        pairs = [
            (fi, gi, rg)
            for fi, r in enumerate(readers)
            for gi, rg in enumerate(r.reader.row_groups)
        ]
        n_groups = len(pairs)
        rows_per = [int(rg.num_rows or 0) for _, _, rg in pairs]
        per_axis = max(1, -(-n_groups // n_axis))
        g_pad = per_axis * n_axis
        if g_pad % n_proc:
            raise ValueError(
                f"axis of {n_axis} devices is not spread evenly over "
                f"{n_proc} processes"
            )
        k = g_pad // n_proc
        mine = [g for g in range(pid * k, (pid + 1) * k)]

        keep = None
        if predicate is not None and n_groups:
            # each host evaluates only ITS block (Bloom probes read from
            # the file; non-owned verdicts are irrelevant once agreed),
            # then one union collective reconciles — a transient probe
            # failure keeps the group conservatively on EVERY host, so
            # shard shapes/num_rows never diverge across processes
            vec = np.zeros(n_groups, np.int64)
            for g in mine:
                if g < n_groups and predicate.may_match_with(
                    readers[pairs[g][0]].reader, pairs[g][2]
                ):
                    vec[g] = 1
            agreed = _agree_max(vec)
            keep = {g for g in range(n_groups) if agreed[g]}
            # pruned rows leave the result: zero their counts so num_rows
            # and the ghost row_mask reflect only surviving groups
            rows_per = [
                r if g in keep else 0 for g, r in enumerate(rows_per)
            ]
        stride = max(rows_per) if rows_per else 0
        uniform = (
            g_pad == n_groups
            and len(set(rows_per)) <= 1
            and (keep is None or len(keep) == n_groups)
        )

        # the scan scheduler's device leg (docs/scan.md): this host's
        # block of groups decodes through the cross-file stage‖ship‖decode
        # pipeline, so it never drains at a file boundary — group 0 of
        # file k+1 stages while the last group of file k decodes
        order = [
            g for g in mine
            if g < n_groups and (keep is None or g in keep)
        ]
        tasks = [(readers[pairs[g][0]], pairs[g][1]) for g in order]
        decoded: Dict[int, Dict[str, object]] = {
            g: cols
            for g, cols in zip(order, iter_dataset_row_groups(tasks, columns))
        }
        # column names must agree across hosts even when a host owns only
        # ghost groups: derive them from the schema, mirroring the engine's
        # naming rule (dotted path for any nested leaf, else the bare name)
        want = set(columns) if columns else None
        names, descs = [], []
        for desc in reader.reader.schema.columns:
            if want and desc.path[0] not in want:
                continue
            names.append(".".join(desc.path) if len(desc.path) > 1 else desc.path[0])
            descs.append(desc)

        # ONE allgather agrees every decode-revealed fact for the whole
        # file: per column [repeated, strings, any_mask, width, vmax,
        # lmax, trailing dim, dtype kind, dtype size]
        meta_local = np.zeros((len(names), 9), np.int64)
        for ci, name in enumerate(names):
            parts = {g: decoded[g][name] for g in decoded}
            if not parts:
                continue
            sample = next(iter(parts.values()))
            repeated = sample.is_repeated
            strings = sample.is_strings
            trail = (
                sample.values.shape[-1]
                if (not strings and sample.values.ndim > 1)
                else 0
            )
            kind, size = _dtype_code(sample.values.dtype)
            meta_local[ci] = [
                int(repeated),
                int(strings),
                int(any(p.mask is not None for p in parts.values())),
                max(p.values.shape[1] for p in parts.values()) if strings else 0,
                max(p.values.shape[0] for p in parts.values()) if repeated else 0,
                max(p.def_levels.shape[0] for p in parts.values()) if repeated else 0,
                trail,
                kind,
                size,
            ]
        meta = _agree_max(meta_local)

        out: Dict[str, object] = {}
        for ci, name in enumerate(names):
            parts = {g: decoded[g][name] for g in decoded}
            rep_flag, str_flag, any_mask, width, vmax, lmax, trail, kind, size = (
                int(v) for v in meta[ci]
            )
            if kind == 0:
                # NO host decoded this column anywhere (e.g. the predicate
                # pruned every row group): derive shape facts from the
                # schema instead of the zeroed agreement vector, so typed
                # ghosts still come back as the right column kind
                rep_flag, str_flag, width, vmax, lmax, trail, vdtype = (
                    _schema_meta(descs[ci], reader.float64_policy)
                )
            else:
                vdtype = np.uint8 if str_flag else _dtype_from_code(kind, size)
            if rep_flag:
                out[name] = _nested_global(
                    parts, mine, rows_per, sharding,
                    bool(str_flag), width, vmax, lmax, vdtype, descs[ci],
                )
            else:
                out[name] = _flat_global(
                    parts, mine, rows_per, stride, uniform, sharding,
                    bool(str_flag), bool(any_mask), width, trail, vdtype,
                )
        return out


def _flat_global(parts, mine, rows_per, stride, uniform,
                 sharding, strings, any_mask, width, trail, vdtype):
    vals, masks, lens, valids = [], [], [], []
    for g in mine:
        if g in parts:
            p, rows = parts[g], rows_per[g]
            v = np.asarray(p.values)
            if strings:
                v = _pad_np(v, stride, width)
            else:
                v = _pad_np(v, stride)
            m = np.zeros(stride, bool)
            if p.mask is not None:
                m[: rows] = np.asarray(p.mask)[:rows]
                v = v.copy()
                v[np.flatnonzero(m[: v.shape[0]])] = 0
            valid = np.arange(stride) < rows
            ln = (
                _pad_np(np.asarray(p.lengths), stride) if strings else None
            )
        else:  # ghost group: all metadata comes from the agreed vector
            shape = (
                (stride, width)
                if strings
                else ((stride, trail) if trail else (stride,))
            )
            v = np.zeros(shape, vdtype)
            m = np.zeros(stride, bool)
            valid = np.zeros(stride, bool)
            ln = np.zeros(stride, np.int32) if strings else None
        vals.append(v)
        masks.append(m)
        valids.append(valid)
        if strings:
            lens.append(ln)

    local_v = np.concatenate(vals) if vals else np.zeros(0, vdtype)
    values = jax.make_array_from_process_local_data(sharding, local_v)
    mask = (
        jax.make_array_from_process_local_data(sharding, np.concatenate(masks))
        if any_mask
        else None
    )
    lengths = (
        jax.make_array_from_process_local_data(
            sharding, np.concatenate([l.astype(np.int32) for l in lens])
        )
        if strings
        else None
    )
    row_mask = (
        None
        if uniform
        else jax.make_array_from_process_local_data(
            sharding, np.concatenate(valids)
        )
    )
    return GlobalColumn(
        values, mask, lengths=lengths, row_mask=row_mask,
        num_rows=sum(rows_per),
    )


def _nested_global(parts, mine, rows_per, sharding,
                   strings, width, vmax, lmax, vdtype, desc):
    vs, ls, ds, rs, counts, grows = [], [], [], [], [], []
    for g in mine:
        if g in parts:
            p = parts[g]
            v = np.asarray(p.values)
            v = _pad_np(v, vmax, width if strings else None)
            d = _pad_np(np.asarray(p.def_levels), lmax)
            r = _pad_np(np.asarray(p.rep_levels), lmax)
            ln = _pad_np(np.asarray(p.lengths), vmax) if strings else None
            counts.append(np.asarray(p.def_levels).shape[0])
            grows.append(rows_per[g])
        else:  # ghost group: all metadata comes from the agreed vector
            v = np.zeros((vmax, width) if strings else (vmax,), vdtype)
            d = np.zeros(lmax, np.int32)
            r = np.zeros(lmax, np.int32)
            ln = np.zeros(vmax, np.int32) if strings else None
            counts.append(0)
            grows.append(0)
        vs.append(v)
        ds.append(d.astype(np.int32))
        rs.append(r.astype(np.int32))
        if strings:
            ls.append(ln.astype(np.int32))

    mk = jax.make_array_from_process_local_data
    gv = mk(sharding, np.stack(vs))
    gl = mk(sharding, np.stack(ls)) if strings else None
    gd = mk(sharding, np.stack(ds))
    gr = mk(sharding, np.stack(rs))
    gc = mk(sharding, np.asarray(counts, np.int32))
    gg = mk(sharding, np.asarray(grows, np.int32))
    return ShardedNestedColumn(desc, gv, gl, gd, gr, gc, gg)
