"""Multi-host sharded decode over DCN — each process reads only its own
row groups' bytes, and the results assemble into global ``jax.Array``s.

The single-host sibling (``parallel.shard``) shards row groups across the
devices one process owns; this module scales the same axis across
*processes* (hosts): process ``p`` owns the contiguous block of row
groups ``[p·k, (p+1)·k)`` (k = n_groups / process_count — contiguous so
the global array preserves file row order), each host decodes its share
locally (never touching other hosts' byte ranges — the DCN
input-sharding pattern SURVEY.md §5 prescribes), and
``jax.make_array_from_process_local_data`` stitches the per-host shards
into one globally-sharded array without any host ever holding the full
column.

Under a single process (tests, the driver's virtual CPU mesh) this
degrades to a plain sharded decode — same code path, one shard.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class GlobalColumn:
    """A globally-sharded decoded column: dense values + null mask."""

    values: jax.Array
    mask: Optional[jax.Array]  # True where null; None when required


def read_sharded_global(
    source,
    mesh: Mesh,
    axis: str = "rg",
    columns: Optional[Sequence[str]] = None,
    float64_policy: str = "auto",
) -> Dict[str, GlobalColumn]:
    """Decode a parquet file into global arrays sharded over ``mesh[axis]``.

    Each process decodes a *contiguous block* of row groups (process p
    owns groups [p·k, (p+1)·k) with k = n_groups / process_count), so the
    assembled global array preserves file row order.  Row groups must be
    uniform (equal row counts) so shards concatenate into a rectangular
    global shape; strings and repeated columns are not supported here
    (use per-group readers for those).  Optional columns return their
    null mask alongside the zero-filled dense values.
    """
    from ..tpu.engine import TpuRowGroupReader

    n_proc = jax.process_count()
    pid = jax.process_index()
    sharding = NamedSharding(mesh, P(axis))

    with TpuRowGroupReader(source, float64_policy=float64_policy) as reader:
        n_groups = reader.num_row_groups
        if n_groups % n_proc:
            raise ValueError(
                f"{n_groups} row groups do not shard evenly over "
                f"{n_proc} processes"
            )
        # fail fast from the schema, before any I/O or device work
        from ..format.parquet_thrift import Type as _T

        for desc in reader.reader.schema.columns:
            if columns and desc.path[0] not in set(columns):
                continue
            if desc.physical_type == _T.BYTE_ARRAY or desc.max_repetition_level:
                raise NotImplementedError(
                    f"column {'.'.join(desc.path)}: strings/repeated "
                    "columns are not supported by read_sharded_global"
                )
        k = n_groups // n_proc
        mine = range(pid * k, (pid + 1) * k)
        parts: Dict[str, list] = {}
        mask_parts: Dict[str, list] = {}
        rows_per_group = None
        for g in mine:
            cols = reader.read_row_group(g, columns)
            for name, dc in cols.items():
                if dc.is_strings or dc.is_repeated:
                    raise NotImplementedError(
                        f"column {name}: strings/repeated columns are not "
                        "supported by read_sharded_global"
                    )
                arr = np.asarray(dc.values)
                if dc.mask is not None:
                    m = np.asarray(dc.mask)
                    arr = np.where(m, 0, arr)
                    mask_parts.setdefault(name, []).append(m)
                if rows_per_group is None:
                    rows_per_group = arr.shape[0]
                elif arr.shape[0] != rows_per_group:
                    raise ValueError(
                        f"row group {g} has {arr.shape[0]} rows != "
                        f"{rows_per_group}; uniform groups required"
                    )
                parts.setdefault(name, []).append(arr)

    out: Dict[str, GlobalColumn] = {}
    for name, arrs in parts.items():
        local = np.concatenate(arrs, axis=0)
        values = jax.make_array_from_process_local_data(sharding, local)
        mask = None
        if name in mask_parts:
            mask = jax.make_array_from_process_local_data(
                sharding, np.concatenate(mask_parts[name], axis=0)
            )
        out[name] = GlobalColumn(values, mask)
    return out
