"""Multi-chip scan placement: the (row group → device) layer.

One process with k local devices decodes a dataset k-wide by
round-robining STAGED row groups across the chips: each group stages on
the host (read + inflate + plan), ships to ITS device on that device's
own ship worker (transfers overlap across chips, stay serialized per
chip), and dispatches its fused decode against that device's persistent
exec-cache entry (the cache key carries ``platform:id``, so k devices
warm k entries).  Delivery to the consumer stays strictly in submission
order — the single-device admission argument, now across devices — so
every read face (``scan_device_groups``, the ``DataLoader``, pushdown,
the compactor's read leg) inherits the fan-out with decoded values
bit-identical to the single-device path (padded widths follow the
existing ``PFTPU_STAGE_WORKERS>1`` contract; docs/multichip.md).

Placement policy (``mesh_devices``):

* on an accelerator backend (platform != "cpu") with more than one
  local device, the mesh is ON by default over all of them;
* on CPU the forced host "devices" share one machine — no speedup, so
  the mesh is opt-in there (tests, parity smokes);
* ``PFTPU_MESH_DEVICES`` overrides either way: ``0``/``1`` disables,
  ``k`` caps the mesh at the first k local devices, ``all`` uses every
  local device regardless of platform.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

__all__ = ["mesh_devices", "mesh_enabled", "DevicePools"]


def mesh_devices() -> List[object]:
    """The scan scheduler's target devices, in placement (round-robin)
    order — ``[]`` when the mesh is off (single-device scheduling).
    See the module docstring for the policy; this never initializes a
    backend beyond what ``jax.local_devices()`` already does."""
    import jax

    env = os.environ.get("PFTPU_MESH_DEVICES", "").strip().lower()
    try:
        devs = list(jax.local_devices())
    except RuntimeError:
        return []
    if env == "all":
        pass
    elif env:
        try:
            k = int(env)
        except ValueError:
            raise ValueError(
                f"PFTPU_MESH_DEVICES must be an integer or 'all', "
                f"got {env!r}"
            ) from None
        if k <= 1:
            return []
        devs = devs[:k]
    elif not devs or devs[0].platform == "cpu":
        # forced host devices share the one CPU: mesh scheduling buys
        # contention, not throughput — opt-in only
        return []
    return devs if len(devs) > 1 else []


def mesh_enabled() -> bool:
    """True when ``mesh_devices()`` would schedule across >1 device."""
    return len(mesh_devices()) > 1


class DevicePools:
    """Per-device single-worker ship pools: one ``ThreadPoolExecutor``
    per mesh device, so H2D transfers OVERLAP across chips while each
    chip's transfers stay serialized (the single-device
    ``sync_transfers`` discipline, per device).  Owns its worker
    threads — with-manage it or ``shutdown()`` in a ``finally``
    (FL-RES001 knows this shape)."""

    def __init__(self, devices, thread_name_prefix: str = "pftpu-devship"):
        self._pools = {}
        self._lock = threading.Lock()
        self._prefix = thread_name_prefix
        self._shut = False
        try:
            for i, d in enumerate(devices or []):
                self._pools[d] = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"{thread_name_prefix}-{i}",
                )
        except BaseException:
            self.shutdown(wait=False)
            raise

    def __len__(self) -> int:
        return len(self._pools)

    def submit(self, device, fn, *args, **kwargs):
        """Submit onto ``device``'s worker (created on first use for a
        device outside the construction set — the big-group and salvage
        stragglers stay schedulable)."""
        with self._lock:
            if self._shut:
                raise RuntimeError("DevicePools is shut down")
            pool = self._pools.get(device)
            if pool is None:
                pool = self._pools[device] = ThreadPoolExecutor(
                    max_workers=1,
                    thread_name_prefix=f"{self._prefix}-{len(self._pools)}",
                )
        return pool.submit(fn, *args, **kwargs)

    def shutdown(self, wait: bool = True) -> None:
        """Join (``wait=True``) or abandon every per-device worker.
        Idempotent; safe on a partially-constructed set."""
        with self._lock:
            pools = list(self._pools.values())
            self._pools.clear()
            self._shut = True
        for p in pools:
            p.shutdown(wait=wait)

    def __enter__(self) -> "DevicePools":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.shutdown(wait=True)
        return None
