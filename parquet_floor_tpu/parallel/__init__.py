"""Device-mesh sharding: row-group/column parallel decode via
jax.sharding, plus the multi-chip scan scheduler's (row group → device)
placement layer (:mod:`.mesh`, docs/multichip.md)."""
