#!/usr/bin/env python
"""Headline benchmark: TPC-H lineitem decode throughput (BASELINE config #2).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "rows/s", "vs_baseline": N}

* value        — rows/s decoding all 16 lineitem columns with the TPU engine
                 (end to end: file read, Snappy decompress, run-table parse,
                 host→HBM transfer, device expand+gather, block_until_ready)
* vs_baseline  — ratio vs the single-thread CPU decode of the same file with
                 the host NumPy engine (the reference-equivalent decoder;
                 the reference publishes no numbers of its own — SURVEY.md §6)

Env knobs: PFTPU_BENCH_ROWS (default 1_000_000), PFTPU_BENCH_REPS (default 3).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# Persistent XLA compile cache: decode-shape compiles are expensive over
# remote TPU links; cache them across bench invocations.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/pftpu_jax_cache")


def main():
    import numpy as np  # noqa: F401

    n_rows = int(os.environ.get("PFTPU_BENCH_ROWS", 1_000_000))
    reps = int(os.environ.get("PFTPU_BENCH_REPS", 3))
    path = os.path.join("/tmp", f"pftpu_bench_lineitem_{n_rows}.parquet")

    from benchmarks.workloads import write_lineitem

    if not os.path.exists(path):
        write_lineitem(path, n_rows)

    from parquet_floor_tpu.format.file_read import ParquetFileReader

    # --- CPU single-thread baseline (host NumPy engine) --------------------
    def cpu_decode():
        with ParquetFileReader(path) as r:
            rows = 0
            for batch in r.iter_row_groups():
                for col in batch.columns:
                    _ = col.values
                rows += batch.num_rows
            return rows

    cpu_decode()  # warm page cache
    cpu_dt = float("inf")
    for _ in range(2):  # best-of: the shared host's CPU clock is noisy
        t0 = time.perf_counter()
        rows = cpu_decode()
        cpu_dt = min(cpu_dt, time.perf_counter() - t0)
    cpu_rps = rows / cpu_dt

    # --- TPU engine --------------------------------------------------------
    import jax

    jax.config.update("jax_enable_x64", True)  # INT64/DOUBLE columns
    from parquet_floor_tpu.tpu.engine import TpuRowGroupReader

    reader = TpuRowGroupReader(path)

    def tpu_decode():
        # streaming scan: every column of each group fully decoded on
        # device, then released — the per-group block also keeps exactly
        # one transfer in flight (see TpuRowGroupReader sync_transfers)
        rows = 0
        for cols in reader.iter_row_groups():
            jax.block_until_ready([c.values for c in cols.values()])
            rows += next(iter(cols.values())).values.shape[0]
            del cols
        return rows

    tpu_decode()  # compile warmup
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        rows_t = tpu_decode()
        best = min(best, time.perf_counter() - t0)
    assert rows_t == rows
    tpu_rps = rows / best
    reader.close()

    result = {
        "metric": "tpch_lineitem_snappy_dict_decode",
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 3),
        "detail": {
            "rows": rows,
            "cpu_rows_per_sec": round(cpu_rps, 1),
            "tpu_rows_per_sec": round(tpu_rps, 1),
            "backend": jax.devices()[0].platform,
            "file_bytes": os.path.getsize(path),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
